"""Per-stage profile of the 4M-row join serve paths (real chip).

Times the indexed co-bucketed join, the unindexed join, and the hybrid
unindexed join (the VERDICT r4 anomaly), with monkeypatched stage timers.
Throwaway diagnostic — not part of the test suite.
"""
import cProfile
import io
import json
import os
import pstats
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import gen_data, log

STAGES = {}


def timed(name, fn):
    def wrap(*a, **k):
        t0 = time.perf_counter()
        out = fn(*a, **k)
        STAGES[name] = STAGES.get(name, 0.0) + time.perf_counter() - t0
        return out

    return wrap


def main():
    n_items = int(os.environ.get("HS_BENCH_ROWS", 4_000_000))
    n_orders = max(n_items // 8, 1)

    from hyperspace_tpu import constants as C
    from hyperspace_tpu.hyperspace import Hyperspace
    from hyperspace_tpu.indexes.covering import CoveringIndexConfig
    from hyperspace_tpu.session import HyperspaceSession

    tmp = tempfile.mkdtemp(prefix="hs_prof_")
    try:
        items_dir, orders_dir = gen_data(tmp, n_items, n_orders)
        session = HyperspaceSession()
        session.conf.set(C.INDEX_SYSTEM_PATH, os.path.join(tmp, "indexes"))
        session.conf.set(C.INDEX_NUM_BUCKETS, 8)
        hs = Hyperspace(session)
        items = session.read.parquet(items_dir)
        orders = session.read.parquet(orders_dir)
        hs.create_index(
            items,
            CoveringIndexConfig(
                "l_idx", ["l_orderkey"], ["l_shipdate", "l_quantity", "l_extendedprice"]
            ),
        )
        hs.create_index(
            orders, CoveringIndexConfig("o_idx", ["o_orderkey"], ["o_custkey", "o_totalprice"])
        )

        def q_join(o, i):
            return o.join(i, on=o["o_orderkey"] == i["l_orderkey"]).select(
                "o_orderkey", "o_custkey", "l_quantity"
            )

        # --- instrument executor internals
        from hyperspace_tpu.execution import executor as X
        from hyperspace_tpu.execution import join_exec as J
        from hyperspace_tpu.io import parquet as pio
        from hyperspace_tpu.io.columnar import ColumnarBatch

        X._exec_bucketed = timed("exec_bucketed", X._exec_bucketed)
        orig_read = pio.read_table
        pio.read_table = timed("pio.read_table", orig_read)
        J_co = J.co_bucketed_join

        def co_timed(lbs, rbs, on, mesh=None, device_min_rows=0):
            t0 = time.perf_counter()
            out = J_co(lbs, rbs, on, mesh, device_min_rows)
            STAGES["co_bucketed_join"] = (
                STAGES.get("co_bucketed_join", 0.0) + time.perf_counter() - t0
            )
            return out

        X.co_bucketed_join_patch = co_timed
        # executor imports co_bucketed_join inside _exec_join; patch module
        J.co_bucketed_join_orig = J_co
        J.co_bucketed_join = co_timed
        J._expand_and_assemble = timed("expand_assemble", J._expand_and_assemble)
        J._verify_keys = timed("verify_keys", J._verify_keys)
        J._assemble = timed("assemble", J._assemble)
        cb_concat = ColumnarBatch.concat
        ColumnarBatch.concat = staticmethod(timed("batch_concat", cb_concat))
        to_arrow = ColumnarBatch.to_arrow
        ColumnarBatch.to_arrow = timed("to_arrow", to_arrow)
        # co_bucketed_join imports these lazily from ops.join — patch there
        from hyperspace_tpu.ops import join as OJ

        OJ.presorted_match_ranges = timed(
            "presorted_match", OJ.presorted_match_ranges
        )
        OJ.bucketed_match_ranges = timed(
            "bucketed_match", OJ.bucketed_match_ranges
        )
        cb_key_reps = ColumnarBatch.key_reps
        ColumnarBatch.key_reps = timed("key_reps", cb_key_reps)

        session.enable_hyperspace()
        q_join(orders, items).collect()  # warm
        for name in ("indexed_join",):
            STAGES.clear()
            t0 = time.perf_counter()
            q_join(orders, items).collect()
            total = time.perf_counter() - t0
            log(f"--- {name}: total {total*1e3:.1f}ms")
            for k, v in sorted(STAGES.items(), key=lambda kv: -kv[1]):
                log(f"    {k:24s} {v*1e3:8.1f}ms")

        # cProfile for detail
        pr = cProfile.Profile()
        pr.enable()
        q_join(orders, items).collect()
        pr.disable()
        s = io.StringIO()
        pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(25)
        log(s.getvalue())

        session.disable_hyperspace()
        q_join(orders, items).collect()  # warm
        STAGES.clear()
        t0 = time.perf_counter()
        q_join(orders, items).collect()
        total = time.perf_counter() - t0
        log(f"--- unindexed_join: total {total*1e3:.1f}ms")
        for k, v in sorted(STAGES.items(), key=lambda kv: -kv[1]):
            log(f"    {k:24s} {v*1e3:8.1f}ms")

        pr = cProfile.Profile()
        pr.enable()
        q_join(orders, items).collect()
        pr.disable()
        s = io.StringIO()
        pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(25)
        log(s.getvalue())

        # hybrid anomaly: append ~3% then unindexed join again
        import pyarrow as pa
        import pyarrow.parquet as pq

        n_extra = max(n_items // 32, 1)
        extra = pa.table(
            {
                "l_orderkey": np.random.default_rng(9).integers(0, n_orders, n_extra),
                "l_shipdate": pa.array(np.full(n_extra, np.datetime64("1998-01-01"))),
                "l_quantity": np.full(n_extra, 7, dtype=np.int64),
                "l_extendedprice": np.full(n_extra, 1.0),
            }
        )
        pq.write_table(extra, os.path.join(items_dir, "appended.parquet"))
        items2 = session.read.parquet(items_dir)
        q_join(orders, items2).collect()  # warm
        STAGES.clear()
        t0 = time.perf_counter()
        q_join(orders, items2).collect()
        total = time.perf_counter() - t0
        log(f"--- unindexed_hybrid_join: total {total*1e3:.1f}ms")
        for k, v in sorted(STAGES.items(), key=lambda kv: -kv[1]):
            log(f"    {k:24s} {v*1e3:8.1f}ms")
        pr = cProfile.Profile()
        pr.enable()
        q_join(orders, items2).collect()
        pr.disable()
        s = io.StringIO()
        pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(25)
        log(s.getvalue())
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
