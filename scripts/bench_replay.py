"""Workload-replay bench — the advisor loop timed end to end.

Builds a throwaway lake, records a canned scenario (query-log format,
replay specs included), replays it through the serve frontend for a
BASELINE, runs the advisor (profile -> what-if recommend -> budgeted
apply), replays the SAME workload again, and runs a second advise()
pass to witness convergence (zero create recommendations once the
recommended index exists).

Prints exactly ONE JSON line on stdout (progress to stderr):

    {"scenario": ..., "records": N, "baseline": {qps, p50_s, ...},
     "after": {...}, "recommended": [names], "applied": N,
     "recs_after_apply": N, "speedup_p50": x}

Usage:  python scripts/bench_replay.py [scenario]
        scenario: skewed (default) | storm | rolling | tenants
Env:    HS_REPLAY_ROWS (default 200_000), HS_REPLAY_QUERIES (default 40),
        HS_REPLAY_FILES (default 8)
"""

import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_lake(data_dir: str, rows: int, files: int) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    per = max(1, rows // files)
    rng = np.random.default_rng(7)
    for i in range(files):
        n = per
        table = pa.table(
            {
                "key": rng.integers(0, 1000, n),
                "ts": np.arange(i * n, i * n + n, dtype=np.int64),
                "payload": rng.integers(0, 1 << 30, n),
            }
        )
        pq.write_table(table, os.path.join(data_dir, f"part-{i:03d}.parquet"))


def make_scenario(name: str, paths, queries: int):
    from hyperspace_tpu.testing import replay

    keys = list(range(0, 1000, 37))
    if name == "storm":
        return replay.hot_key_storm(
            paths, "key", 111, keys, queries, project=["key", "payload"]
        )
    if name == "rolling":
        marks = list(range(0, queries * 500, 500))[: max(1, queries // 4)]
        return replay.rolling_appends(paths, "ts", marks)
    if name == "tenants":
        half = queries // 2
        return replay.tenant_mix(
            paths, "key", keys,
            {"interactive": half, "batch": queries - half},
            project=["key", "payload"],
        )
    return replay.skewed_keys(
        paths, "key", keys, queries, project=["key", "payload"]
    )


def main() -> int:
    scenario = sys.argv[1] if len(sys.argv) > 1 else "skewed"
    rows = int(os.environ.get("HS_REPLAY_ROWS", 200_000))
    queries = int(os.environ.get("HS_REPLAY_QUERIES", 40))
    files = int(os.environ.get("HS_REPLAY_FILES", 8))

    from hyperspace_tpu import constants as C
    from hyperspace_tpu.advisor import advise, apply_recommendations
    from hyperspace_tpu.session import HyperspaceSession
    from hyperspace_tpu.testing import replay as replay_mod

    root = tempfile.mkdtemp(prefix="hs_bench_replay_")
    data_dir = os.path.join(root, "lake")
    os.makedirs(data_dir)
    try:
        log(f"building lake: {rows} rows x {files} files")
        build_lake(data_dir, rows, files)
        paths = [data_dir]
        records = make_scenario(scenario, paths, queries)
        obs_dir = os.path.join(root, "obs")
        replay_mod.record_workload(records, obs_dir)

        session = HyperspaceSession()
        session.conf.set(C.INDEX_SYSTEM_PATH, os.path.join(root, "indexes"))
        session.enable_hyperspace()

        log(f"baseline replay: {len(records)} records")
        baseline = replay_mod.replay_records(session, records)

        log("advising")
        report = advise(session, directory=obs_dir)
        recs = report.recommendations
        log(f"recommendations: {[r.index_name for r in recs]}")
        summary = (
            apply_recommendations(session, recs, force=True)
            if recs
            else {"applied": 0}
        )

        after = replay_mod.replay_records(session, records)
        report2 = advise(session, directory=obs_dir)
        creates_after = [
            r for r in report2.recommendations if r.kind == "create"
        ]
        out = {
            "scenario": scenario,
            "records": len(records),
            "baseline": baseline.to_dict(),
            "after": after.to_dict(),
            "recommended": [r.index_name for r in recs],
            "applied": summary["applied"],
            "recs_after_apply": len(creates_after),
            "speedup_p50": round(
                baseline.p50_s / after.p50_s, 3
            ) if after.p50_s > 0 else 0.0,
        }
        print(json.dumps(out), flush=True)
        return 0
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
