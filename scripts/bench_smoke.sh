#!/usr/bin/env bash
# Tiny-row, CPU-only bench smoke: exercises the full serve pipeline
# (build, point filter, co-bucketed join, serve cache, hybrid scan with
# cached delta, delta refresh, z-order, data skipping) end to end in
# about a minute, so the pipelined code paths run on every CI pass —
# not only in the 4M-row chip benches. The numbers are NOT meaningful
# (tiny rows, host backend); the exit code and the single JSON line are.
# An 8-device CPU mesh is forced so the mesh ladder rows run the sharded
# build/serve tail (shard_map all-to-all + per-shard sort/write/merge).
#
# Usage: scripts/bench_smoke.sh  [rows]   (default 100000)
set -euo pipefail
cd "$(dirname "$0")/.."
ROWS="${1:-${HS_BENCH_ROWS:-100000}}"
if [ "$ROWS" -gt 100000 ]; then
    echo "bench_smoke: capping rows at 100000 (got $ROWS)" >&2
    ROWS=100000
fi
# The slow-marked serve stress suite (64 clients, budgeted cache,
# concurrent refresh) is excluded from tier-1 to keep it fast; it runs
# here so every CI pass exercises the contention rungs — under the
# runtime LOCK WITNESS (testing/lock_witness.py): every
# SHARED_STATE-registered lock records its acquisitions and observed
# ordering edges, and hslint --witness then cross-checks the artifact
# against the static lock model. A witnessed edge the model lacks is a
# hard failure (model gap).
WITNESS="$(mktemp -t hs_lock_witness.XXXXXX.json)"
rm -f "$WITNESS"
HS_LOCK_WITNESS="$WITNESS" JAX_PLATFORMS=cpu \
    python -m pytest tests/test_serve_stress.py tests/test_serve_frontend.py \
    -q -m 'slow or not slow' -p no:cacheprovider
test -s "$WITNESS" || { echo "bench_smoke: lock witness artifact missing" >&2; exit 1; }
JAX_PLATFORMS=cpu python -m hyperspace_tpu.analysis hyperspace_tpu/ \
    --witness "$WITNESS"
echo "bench_smoke: lock-witness cross-check ok (zero model gaps)" >&2
rm -f "$WITNESS"
# The multi-host plane rides the same doctrine: the 2-process dryrun runs
# under the COLLECTIVE witness (testing/collective_witness.py) — every
# COLLECTIVE_SITES-registered call site records each process's ordered
# collective sequence into <prefix>.p<i>.json — and hslint --witness
# merges the per-process artifacts, gating on zero cross-process sequence
# divergence and zero unregistered witnessed sites (HS804), including
# through the coordinator-gated CREATE metadata path the dryrun drives.
CW_DIR="$(mktemp -d -t hs_collective_witness.XXXXXX)"
CW="$CW_DIR/cw"
HS_COLLECTIVE_WITNESS="$CW" JAX_PLATFORMS=cpu python scripts/dryrun_multihost.py
test -s "$CW.p0.json" && test -s "$CW.p1.json" \
    || { echo "bench_smoke: collective witness artifacts missing" >&2; exit 1; }
JAX_PLATFORMS=cpu python -m hyperspace_tpu.analysis hyperspace_tpu/ \
    --witness "$CW"
echo "bench_smoke: collective-witness cross-check ok (zero divergence)" >&2
rm -rf "$CW_DIR"
# The bench run itself rides the RESIDENCY witness
# (testing/residency_witness.py): every ALLOC_SITES-registered
# allocation site records its per-call peak bytes, and hslint --witness
# then cross-checks the artifact against the static bound model
# (memory.py). A witnessed site the registry lacks, or a peak past its
# declared bound-class ceiling, is a hard failure (HS1004 model gap).
RESW="$(mktemp -t hs_residency_witness.XXXXXX.json)"
rm -f "$RESW"
OUT=$(JAX_PLATFORMS=cpu \
HS_RESIDENCY_WITNESS="$RESW" \
HS_BENCH_FORCE_CPU_DEVICES=8 \
HS_BENCH_ROWS="$ROWS" \
HS_BENCH_REPS="${HS_BENCH_REPS:-2}" \
HS_BENCH_LADDER="$ROWS" \
HS_BENCH_MESH="${HS_BENCH_MESH:-1,8}" \
HS_BENCH_MESH_ROWS="$ROWS" \
HS_BENCH_FLEET="${HS_BENCH_FLEET:-2}" \
HS_BENCH_FLEET_ITERS="${HS_BENCH_FLEET_ITERS:-4}" \
HS_BENCH_FLEET_ROWS="${HS_BENCH_FLEET_ROWS:-20000}" \
HS_BENCH_STREAM_LADDER="$ROWS" \
HS_BENCH_STREAM_MAX_BYTES="${HS_BENCH_STREAM_MAX_BYTES:-65536}" \
python bench.py)
echo "$OUT"
test -s "$RESW" || { echo "bench_smoke: residency witness artifact missing" >&2; exit 1; }
JAX_PLATFORMS=cpu python -m hyperspace_tpu.analysis hyperspace_tpu/ \
    --witness "$RESW"
echo "bench_smoke: residency-witness cross-check ok (zero model gaps, bounds held)" >&2
rm -f "$RESW"
# the pruned filter path must actually have run: the z-order row's
# zone-map telemetry is part of the bench JSON contract — and so are the
# mesh ladder rows (a >1-device rung must have run the sharded tail and
# recorded shuffle skew telemetry)
echo "$OUT" | python -c '
import json, sys
d = json.loads(sys.stdin.read())
zp = d["zorder_prune"]
assert zp["row_groups_total"] > 0, "rangeprune telemetry missing"
assert "zonemap_hit_rate" in zp, zp
assert "zorder_range_pruneoff_p50_ms" in d, "prune A/B leg missing"
# the fused serve-pipeline compiler must actually have run on both
# aggregate rows (the A/B legs are meaningless if the on leg silently
# fell back to the interpreted chain)
for row in ("filter_agg", "grouped_agg"):
    fa = d[row]
    assert fa["fused_ran"], f"{row}: fused pipeline did not run: {fa}"
    assert fa["stats"]["rows_scanned"] > 0, fa
    assert fa["stats"]["chunks"] >= 1, fa
assert d["grouped_agg"]["stats"]["groups"] > 1, d["grouped_agg"]
print("bench_smoke: fused pipeline ok:", d["filter_agg"]["stats"],
      d["grouped_agg"]["stats"], file=sys.stderr)
# the aggregate index plane (docs/agg-serve.md) must have answered the
# fully-covered grouped point aggregate FROM THE SIDECAR: metadata path
# fired, every selected row group folded from persisted partials, ZERO
# parquet rows read; and the approximate plane must have produced a
# bounded estimate whose 95% interval contained the exact answer
am = d["agg_metadata"]
assert am["metadata_ran"], f"metadata plane did not run: {am}"
assert am["stats"]["row_groups_scanned"] == 0, am
assert am["stats"]["rows_scanned"] == 0, am
assert am["stats"]["row_groups_metadata"] == am["stats"]["row_groups_total"], am
assert am["stats"]["groups"] > 1, am
ap = d["agg_approx"]
assert ap["count_bound_held"] and ap["sum_bound_held"], ap
assert ap["stats"]["sample_rows"] > 0, ap
assert ap["stats"]["sample_rows"] < ap["stats"]["population_rows"], ap
print("bench_smoke: aggregate plane ok:", am["stats"], ap["stats"],
      file=sys.stderr)
# the concurrent serve frontend must have run its contention ladder
# (incl. the 8- and 64-client rungs) with the cache budget holding, and
# the fault-injection rung must have fired EVERY injection point at
# least once with zero frontend failures (retry/degrade answered
# bit-identically — the asserts live in bench.py; here we require the
# evidence that they ran)
sc = {r["clients"]: r for r in d["serve_concurrency"]}
for clients in (1, 8, 64):
    r = sc[clients]
    assert r["queries"] == clients * 8, r
    assert r["p50_ms"] > 0 and r["p99_ms"] >= r["p50_ms"], r
    assert r["qps"] > 0, r
    assert r["cache_high_water_bytes"] <= r["cache_max_bytes"], r
# the obs plane (docs/observability.md): the interleaved on/off A/B
# rung must prove the structural contract — every query in the
# concurrency rung produced exactly ONE root span and the querylog
# gained exactly one schema-valid row per execution (the replay +
# schema validation of every written row runs inside bench.py; here we
# require the evidence it ran). Overhead on tiny smoke rows is noise —
# the <=5% p50 bar is asserted by bench.py itself at the 4M rung.
so = d["serve_obs"]
assert so["executions"] > 0, so
assert so["roots"] == so["executions"], so
assert so["querylog_rows"] == so["executions"], so
assert so["p50_on_ms"] > 0 and so["p50_off_ms"] > 0, so
print("bench_smoke: obs plane ok:", so, file=sys.stderr)
# the advisor closed loop (docs/advisor.md): the canned skewed replay
# must have produced create recommendation(s) whose top pick indexes
# the workload filter key (the bench-fastest index for a point
# lookup), the budgeted apply must have executed it, the second
# advise() pass must converge to ZERO create recommendations, and the
# post-apply replay must hold QPS within tolerance of the baseline
# (tiny smoke rows can favor brute scans; the index must still never
# fall off a cliff)
adv = d["advisor"]
assert adv["recommended"], adv
assert adv["top_indexed_columns"][0] == "key", adv
assert adv["applied"] >= 1, adv
assert adv["creates_after_apply"] == 0, adv
assert 0.2 <= adv["qps_ratio"] <= 5.0, adv
assert adv["baseline_p50_ms"] > 0 and adv["after_p50_ms"] > 0, adv
print("bench_smoke: advisor loop ok:", adv, file=sys.stderr)
fi = d["fault_injection"]
for point in ("parquet_read", "kernel_dispatch", "log_read", "cache_insert"):
    assert fi["fired"].get(point, 0) >= 1, (point, fi)
assert fi["frontend_failed"] == 0, fi
assert fi["frontend_retries"] >= 1 and fi["frontend_degraded"] >= 1, fi
# the chaos rung (crash-safe lifecycle, docs/recovery.md) must have
# crashed at least one writer and recovered with ZERO stranded log
# entries, ZERO orphan files after GC and ZERO serve mismatches vs the
# crash-free replica
ch = d["chaos"]
assert ch["crashes_fired"] >= 1, ch
assert ch["rolled_back"] >= 1, ch
assert ch["stranded_after_recovery"] == 0, ch
assert ch["orphans_after_gc"] == 0, ch
assert ch["serve_mismatches"] == 0, ch
assert ch["serves_verified"] >= 1, ch
print("bench_smoke: chaos recovery ok:", ch, file=sys.stderr)
# the multi-process fleet (serve/fleet.py, docs/fleet-serve.md): N real
# frontend processes over one lake — every rung must report ZERO wrong
# answers, ZERO leaked pin files, ZERO leaked fast-plane member/socket
# files and a POSITIVE dedup count on SOME plane (claim/spool wins,
# owner-routed handoffs, or fast result-cache hits), and the chaos rung
# must have kill -9ed a frontend mid-serve with the survivors still
# bit-identical
fl = d["fleet_ladder"]
assert fl, "fleet ladder rows missing"
for r in fl:
    assert r["wrong_answers"] == 0, r
    assert r["leaked_pin_files"] == 0, r
    assert r["leaked_fast_members"] == 0, r
    dedup = (r["cross_process_dedup"] + r["fast_handoffs"]
             + r["fast_result_hits"])
    assert dedup > 0, r
    assert r["qps"] > 0 and r["workers_reporting"] == r["processes"], r
# the fast data plane gates (ISSUE 20): the 2-proc rung must witness
# >=1 PUSHED fanout event (the parent phase-2 refresh arriving over
# the socket, not the pollMs scan) and >=1 spool-free owner-routed
# result handoff; every routed probe differentially verified
r2 = next((r for r in fl if r["processes"] == 2), fl[0])
assert r2["fast_frontends"] == r2["processes"], r2
assert r2["fast_push_received"] >= 1, r2
assert r2["fast_handoffs"] >= 1, r2
assert r2["probe_mismatches"] == 0, r2
fc = d["fleet_chaos"]
assert fc["killed"], fc
assert fc["workers_reporting"] == fc["processes"] - 1, fc
assert fc["wrong_answers"] == 0 and fc["leaked_pin_files"] == 0, fc
# fast -> durable degradation witnessed with zero wrong answers: the
# surviving probes at the dead owner paid one failed connect and fell
# back to the claim/spool plane bit-identically
assert fc["fast_fallbacks"] >= 1, fc
assert fc["leaked_fast_members"] == 0, fc
print("bench_smoke: fleet ok:",
      [(r["processes"], r["qps"],
        r["cross_process_dedup"] + r["fast_handoffs"]) for r in fl],
      "fast: push recv", r2["fast_push_received"],
      "handoffs", r2["fast_handoffs"],
      "chaos fallbacks", fc["fast_fallbacks"], file=sys.stderr)
print("bench_smoke: serve concurrency ok:",
      {c: (sc[c]["p50_ms"], sc[c]["p99_ms"], sc[c]["qps"]) for c in sc},
      file=sys.stderr)
print("bench_smoke: fault matrix ok:", fi, file=sys.stderr)
mesh = d["mesh_ladder"]
assert mesh, "mesh ladder rows missing"
multi = [r for r in mesh if r["devices"] > 1]
assert multi, f"no >1-device mesh rung ran: {mesh}"
for r in multi:
    assert r["build_rows_per_sec"] > 0, r
    assert r["build_stage_seconds"].get("tail_shards", 0) > 1, (
        "sharded tail did not run per shard: %r" % r
    )
    assert "skew_ratio" in {k.replace("shuffle_", "") for k in r["shuffle"]}, r
    # the exchange-strategy plane must have reported which strategy ran,
    # and on this CPU rung `auto` must have resolved to the host-side
    # exchange (the simulation never pays ICI-emulation costs) with the
    # pack/exchange/unpack stage telemetry recorded
    assert r["shuffle"].get("shuffle_strategy") == "host", (
        "CPU mesh rung did not auto-select the host exchange: %r"
        % r["shuffle"]
    )
    for stage in ("pack", "exchange", "unpack"):
        assert f"shuffle_{stage}_s" in r["shuffle"], r["shuffle"]
    assert "shuffle_skew_ratio_max" in r["shuffle"], r["shuffle"]
    assert "shuffle_skew_ratio_mean" in r["shuffle"], r["shuffle"]
print("bench_smoke: rangeprune telemetry ok:", zp, file=sys.stderr)
print("bench_smoke: mesh ladder ok:", multi[-1]["build_stage_seconds"],
      multi[-1]["shuffle"], file=sys.stderr)
# resident-set telemetry (memory.py ALLOC_SITES doctrine): every ladder
# rung must carry the RSS high-water, and the witnessed run must have
# recorded per-site peak bytes for at least the core serve sites (the
# cross-check against the bound model already gated above)
res = d["residency"]
assert res["rss_high_water_bytes"] > 0, res
assert res["witnessed_sites"] > 0, res
peaks = res["witness_peak_bytes_by_site"]
site = "hyperspace_tpu.io.parquet.read_table"
assert site in peaks and peaks[site] > 0, (site, sorted(peaks))
# the join rungs prepare sides via the pipelined streaming path on the
# clean serve shape, the sequential twin otherwise — either witnesses
prep_sites = [
    "hyperspace_tpu.execution.join_exec.prepare_join_side",
    "hyperspace_tpu.execution.join_exec.prepare_join_side_pipelined",
]
assert any(peaks.get(p, 0) > 0 for p in prep_sites), sorted(peaks)
for r in d["build_ladder"] + d["mesh_ladder"]:
    assert r["rss_high_water_bytes"] > 0, r
print("bench_smoke: residency telemetry ok:",
      {"rss_high_water_bytes": res["rss_high_water_bytes"],
       "witnessed_sites": res["witnessed_sites"]}, file=sys.stderr)
# the out-of-core streaming rung (docs/out-of-core.md): the tiny wave
# budget must have packed the join into MULTIPLE waves (the streaming
# path actually ran, not the materializing fallback), the spill tier
# must have round-tripped at least one demote AND restore, and the
# output must equal the materializing baseline row for row. Bound-class
# violations are impossible here by construction: the residency-witness
# cross-check above already gated the whole run (incl. the wave-budget
# and spill-bounded sites) against the ALLOC_SITES model
st = d["stream_ladder"]
assert st, "stream ladder rows missing"
for r in st:
    assert r["stream_waves"] > 1, f"streaming path did not wave-pack: {r}"
    assert r["stream_buckets"] >= r["stream_waves"], r
    assert r["spill_demotes"] >= 1, f"spill tier never demoted: {r}"
    assert r["spill_restores"] >= 1, f"spill tier never restored: {r}"
    assert r["stream_stage_ms"].get("stream_wave", 0) > 0, r
    assert r["rows_out"] == r["materializing_baseline"]["rows_out"], r
    assert r["rss_high_water_bytes"] > 0, r
print("bench_smoke: out-of-core stream ok:",
      [(r["rows"], r["stream_waves"], r["spill_demotes"],
        r["spill_restores"]) for r in st], file=sys.stderr)
'
