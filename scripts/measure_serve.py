"""Quick serve-path timings on the real chip: filter + join, cached vs
uncached vs unindexed. Throwaway diagnostic."""
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import gen_data, log, timeit


def p50(fn, reps):
    return timeit(fn, reps)["p50"]


def main():
    n_items = int(os.environ.get("HS_BENCH_ROWS", 4_000_000))
    n_orders = max(n_items // 8, 1)
    reps = 5

    from hyperspace_tpu import constants as C
    from hyperspace_tpu.hyperspace import Hyperspace
    from hyperspace_tpu.indexes.covering import CoveringIndexConfig
    from hyperspace_tpu.session import HyperspaceSession

    tmp = tempfile.mkdtemp(prefix="hs_serve_")
    try:
        items_dir, orders_dir = gen_data(tmp, n_items, n_orders)
        session = HyperspaceSession()
        session.conf.set(C.INDEX_SYSTEM_PATH, os.path.join(tmp, "indexes"))
        session.conf.set(C.INDEX_NUM_BUCKETS, 8)
        hs = Hyperspace(session)
        items = session.read.parquet(items_dir)
        orders = session.read.parquet(orders_dir)
        hs.create_index(
            items,
            CoveringIndexConfig(
                "l_idx",
                ["l_orderkey"],
                ["l_shipdate", "l_quantity", "l_extendedprice"],
            ),
        )
        hs.create_index(
            orders,
            CoveringIndexConfig("o_idx", ["o_orderkey"], ["o_custkey", "o_totalprice"]),
        )
        session.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
        key = int(n_orders // 3)

        def q_filter(df):
            return df.filter(df["l_orderkey"] == key).select(
                "l_orderkey", "l_shipdate", "l_quantity"
            )

        def q_join(o, i):
            return o.join(i, on=o["o_orderkey"] == i["l_orderkey"]).select(
                "o_orderkey", "o_custkey", "l_quantity"
            )

        session.enable_hyperspace()
        # uncached
        q_filter(items).collect()
        f_un = p50(lambda: q_filter(items).collect(), reps)
        q_join(orders, items).collect()
        j_un = p50(lambda: q_join(orders, items).collect(), reps)
        # cached
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        t0 = time.perf_counter()
        q_filter(items).collect()
        f_warmup = time.perf_counter() - t0
        f_ca = p50(lambda: q_filter(items).collect(), reps)
        t0 = time.perf_counter()
        q_join(orders, items).collect()
        j_warmup = time.perf_counter() - t0
        j_ca = p50(lambda: q_join(orders, items).collect(), reps)
        log(f"cache stats: {session.serve_cache.hits} hits, "
            f"{session.serve_cache.misses} misses, "
            f"{session.serve_cache.resident_bytes/1e6:.0f}MB resident")
        session.conf.set(C.SERVE_CACHE_ENABLED, False)
        session.disable_hyperspace()
        q_filter(items).collect()
        f_raw = p50(lambda: q_filter(items).collect(), reps)
        q_join(orders, items).collect()
        j_raw = p50(lambda: q_join(orders, items).collect(), reps)
        log(
            f"filter: unindexed {f_raw*1e3:.1f}ms | indexed {f_un*1e3:.1f}ms "
            f"({f_raw/f_un:.1f}x) | cached {f_ca*1e3:.2f}ms ({f_raw/f_ca:.1f}x, "
            f"cold-vs-cached {f_un/f_ca:.1f}x, warmup {f_warmup*1e3:.0f}ms)"
        )
        log(
            f"join:   unindexed {j_raw*1e3:.1f}ms | indexed {j_un*1e3:.1f}ms "
            f"({j_raw/j_un:.2f}x) | cached {j_ca*1e3:.1f}ms ({j_raw/j_ca:.2f}x, "
            f"cold-vs-cached {j_un/j_ca:.2f}x, warmup {j_warmup*1e3:.0f}ms)"
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
