"""A/B: Pallas vs XLA murmur3 bucket-hash kernel on DEVICE-RESIDENT data.

The honest frame for the Pallas question (BASELINE.md): on this one-chip
setup every build/serve batch is host-resident and transfer dominates, so
the numpy twin wins regardless of kernel quality. This measures the
kernels where they actually live — inputs already in HBM, outputs left in
HBM — i.e. the mesh-sharded multi-chip regime's per-shard work.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from hyperspace_tpu.ops.hash import (
    _PALLAS_BLOCK_N,
    _bucket_ids_words,
    bucket_ids_host,
    bucket_ids_pallas,
    split_words_np,
)


def bench(fn, *args, reps=20):
    fn(*args).block_until_ready()  # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn(*args).block_until_ready()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def main():
    print(f"platform: {jax.devices()[0].platform}", file=sys.stderr)
    pallas_jit = jax.jit(
        bucket_ids_pallas, static_argnames=("num_buckets", "seed")
    )
    for n_m in (4, 16, 64):
        n = n_m * 1024 * 1024  # multiple of _PALLAS_BLOCK_N (64Ki)
        assert n % _PALLAS_BLOCK_N == 0
        rng = np.random.default_rng(7)
        reps = rng.integers(-(2**62), 2**62, (1, n)).astype(np.int64)
        words = jnp.asarray(split_words_np(reps))  # device-resident input
        t_xla = bench(_bucket_ids_words, words, 8, 42)
        t_pallas = bench(pallas_jit, words, 8, 42)
        ok = np.array_equal(
            np.asarray(bucket_ids_pallas(words, 8)),
            bucket_ids_host(reps, 8),
        )
        gbps = n * 8 / t_pallas / 1e9
        print(
            f"n={n_m}Mi  xla={t_xla*1e3:8.3f}ms  pallas={t_pallas*1e3:8.3f}ms  "
            f"ratio={t_xla/t_pallas:5.2f}x  pallas_bw={gbps:6.1f}GB/s  exact={ok}"
        )


if __name__ == "__main__":
    main()
