"""Mesh build-throughput harness — the MULTICHIP_r0N artifact producer.

Earlier MULTICHIP artifacts recorded only rc/ok of the tiny-shape
correctness dryrun; with the sharded build/serve tail the artifact must
record THROUGHPUT: this script forces an ``n``-device CPU mesh (or uses
real devices), runs the full framework dryrun first as a correctness
gate, then times warm covering builds at ``HS_MESH_ROWS`` on 1 device
and on the full mesh — once per exchange strategy in
``HS_MESH_STRATEGIES`` — with the per-stage breakdown (sort/write busy
seconds across the shard tails vs ``tail_wall``) and the exchange
plane's telemetry: chosen strategy, pack/exchange/unpack stage seconds
and the cap/skew numbers. ``mesh_speedup`` compares the single-device
build against the FIRST listed strategy's full-mesh build (default
``auto``, the shipping configuration).

Prints exactly ONE JSON line on stdout (progress to stderr), in the
MULTICHIP artifact shape (n_devices / rc / ok / skipped / tail) plus the
throughput fields.

Usage:  python scripts/bench_mesh.py [n_devices]     (default 8)
Env:    HS_MESH_ROWS (default 64_000_000), HS_MESH_BUCKETS (default 8),
        HS_MESH_SIZES (default "1,<n_devices>"),
        HS_MESH_STRATEGIES (default "auto" — e.g. "auto,flat,compact,
        twostage" for a per-strategy A/B artifact)
"""

import io
import json
import os
import shutil
import sys
import tempfile
import time
from contextlib import redirect_stdout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def timed_build(devices, rows, data_dir, num_buckets, strategy="auto"):
    """Warm covering-index build on ``devices`` under ``strategy``: first
    build pays the compiles/caches, the timed second build is steady
    state."""
    from hyperspace_tpu import constants as C
    from hyperspace_tpu.hyperspace import Hyperspace
    from hyperspace_tpu.indexes.covering import CoveringIndexConfig
    from hyperspace_tpu.indexes.covering_build import (
        last_build_breakdown,
        last_build_telemetry,
    )
    from hyperspace_tpu.session import HyperspaceSession

    root = tempfile.mkdtemp(prefix=f"hs_meshidx_{len(devices)}_")
    try:
        session = HyperspaceSession(devices=devices)
        session.conf.set(C.INDEX_SYSTEM_PATH, root)
        session.conf.set(C.INDEX_NUM_BUCKETS, num_buckets)
        session.conf.set(C.BUILD_EXCHANGE_STRATEGY, strategy)
        if strategy == "twostage":
            # single-controller simulation: carve the mesh in two hosts
            session.conf.set(C.BUILD_EXCHANGE_TWOSTAGE_HOSTS, 2)
        hs = Hyperspace(session)
        df = session.read.parquet(data_dir)
        cfg = CoveringIndexConfig(
            "mesh_idx",
            ["l_orderkey"],
            ["l_shipdate", "l_quantity", "l_extendedprice"],
        )
        hs.create_index(df, cfg)  # warm compiles/caches
        hs.delete_index("mesh_idx")
        hs.vacuum_index("mesh_idx")
        session.index_manager.clear_cache()
        t0 = time.perf_counter()
        hs.create_index(df, cfg)
        warm = time.perf_counter() - t0
        telem = dict(last_build_telemetry)
        return {
            "devices": len(devices),
            "rows": rows,
            "strategy": strategy,
            "exchange_strategy": telem.get("shuffle_strategy", ""),
            "exchange_stage_seconds": {
                stage: telem.get(f"shuffle_{stage}_s", 0.0)
                for stage in ("pack", "exchange", "unpack")
            },
            "build_warm_s": round(warm, 3),
            "build_rows_per_sec": round(rows / warm),
            "build_stage_seconds": {
                k: round(v, 3) for k, v in last_build_breakdown.items()
            },
            "shuffle": telem,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def main() -> int:
    n_devices = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    rows = int(os.environ.get("HS_MESH_ROWS", 64_000_000))
    num_buckets = int(os.environ.get("HS_MESH_BUCKETS", 8))
    sizes_env = os.environ.get("HS_MESH_SIZES", f"1,{n_devices}")
    strategies = [
        s.strip()
        for s in os.environ.get("HS_MESH_STRATEGIES", "auto").split(",")
        if s.strip()
    ]

    import __graft_entry__ as graft

    jax = graft._ensure_devices(n_devices)

    out = {
        "n_devices": n_devices,
        "rc": 0,
        "ok": False,
        "skipped": False,
        "rows": rows,
        "num_buckets": num_buckets,
        "strategies": strategies,
    }
    # 1. correctness gate: the full tiny-shape framework dryrun (create/
    # join/hybrid/refresh/delete/optimize, differentially checked)
    buf = io.StringIO()
    try:
        with redirect_stdout(buf):
            graft.dryrun_multichip(n_devices)
    except Exception as exc:  # artifact must record the failure, not die
        out["rc"] = 1
        out["tail"] = f"{buf.getvalue()}\nDRYRUN FAILED: {exc!r}"
        print(json.dumps(out))
        return 1
    tail = buf.getvalue().strip().splitlines()
    out["tail"] = tail[-1] if tail else ""
    log(out["tail"])

    # 2. throughput: warm builds per (mesh size, strategy) over one
    # shared dataset; single-device rungs run once (no exchange)
    import bench as _bench

    tmp = tempfile.mkdtemp(prefix="hs_meshbench_")
    try:
        log(f"generating {rows:,}-row dataset ...")
        items_dir, _orders = _bench.gen_data(tmp, rows, max(rows // 8, 1))
        mesh = []
        for d in [int(x) for x in sizes_env.split(",") if x.strip()]:
            if d > len(jax.devices()):
                continue
            for strategy in strategies if d > 1 else strategies[:1]:
                log(f"building on {d} device(s), strategy={strategy} ...")
                rung = timed_build(
                    jax.devices()[:d], rows, items_dir, num_buckets, strategy
                )
                log(
                    f"mesh{d}/{strategy}"
                    f"[{rung['exchange_strategy'] or 'none'}]: "
                    f"{rung['build_warm_s']}s warm "
                    f"({rung['build_rows_per_sec']:,} rows/s); "
                    f"stages: {rung['build_stage_seconds']}; "
                    f"exchange: {rung['exchange_stage_seconds']}"
                )
                mesh.append(rung)
        out["mesh"] = mesh
        base = [r for r in mesh if r["devices"] == 1]
        full = [
            r
            for r in mesh
            if r["devices"] > 1 and r["strategy"] == strategies[0]
        ]
        if base and full:
            out["mesh_speedup"] = round(
                base[0]["build_warm_s"] / full[0]["build_warm_s"], 3
            )
        out["ok"] = True
        print(json.dumps(out))
        return 0
    except MemoryError:
        out["skipped"] = True
        out["tail"] += "\nmesh bench skipped: MemoryError"
        print(json.dumps(out))
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
