"""TPC-H (22 queries) plan-stability golden harness, via the SQL surface.

The reference checks in all 103 TPC-DS queries and snapshots simplified
physical plans, failing CI on any plan change
(``goldstandard/PlanStabilitySuite.scala:46-290``). This is the same
machinery at real TPC-H breadth: a deterministic 8-table TPC-H-shaped
dataset (SF ~0.01 row counts), a fixed index inventory, and all 22
queries expressed in the engine's SQL dialect. Golden files contain the
simplified optimized plan WITH indexes and WITHOUT (both sections), and
each query is additionally executed differentially (indexed answer ==
unindexed answer).

Dialect adaptations (the engine's SQL has no subqueries, outer joins,
CASE, LIKE, HAVING, or computed select expressions; adaptations keep
each query's predicate structure, grouping and ordering, and keep the
table set/join graph EXCEPT where noted below):

  q2   min-supplycost subquery dropped (join graph + region filter kept)
  q4   EXISTS -> inner join on l_orderkey (count semantics over matches)
  q7/q8  nation self-joins use the pre-renamed ``nation2`` view;
         CASE/year-extraction replaced by plain aggregates
  q9   REDUCED table set: part/partsupp/supplier/nation2 only (the
       lineitem/orders legs served the dropped profit expression)
  q13  LEFT OUTER JOIN -> inner join; count(distinct) -> count
  q14/q16  LIKE patterns -> equality/IN on the categorical column
  q11/q15/q18  HAVING / subquery thresholds dropped or made literal
  q17  0.2*avg(quantity) subquery -> literal quantity threshold
  q19  OR-of-conjunct structure kept verbatim (brand x quantity bands)
  q20  REDUCED table set: supplier/nation only (the part/partsupp/
       lineitem legs existed solely for the nested EXISTS chain)
  q22  REDUCED table set: customer only (the NOT-EXISTS orders probe
       and phone-prefix/acctbal subqueries became literal predicates)
  revenue measures are SUM(l_extendedprice) (no computed expressions)

Regenerate after an intentional planner change with:

    HS_GENERATE_GOLDEN_FILES=1 python -m pytest tests/test_tpch_plan_stability.py
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.indexes.dataskipping import DataSkippingIndexConfig
from hyperspace_tpu.indexes.sketches import MinMaxSketch

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "goldstandard", "tpch")

# SF ~0.01 row counts
N_REGION, N_NATION, N_SUPP = 5, 25, 100
N_CUST, N_PART, N_PARTSUPP = 1500, 2000, 8000
N_ORDERS, N_LINEITEM = 15000, 60000

_SEGMENTS = ["BUILDING", "MACHINERY", "AUTOMOBILE", "HOUSEHOLD", "FURNITURE"]
_NATIONS = [
    "ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
    "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ", "JAPAN",
    "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU", "CHINA",
    "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA", "UNITED KINGDOM",
    "UNITED STATES",
]
_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
_TYPES = ["PROMO", "STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY"]
_CONTAINERS = ["SM CASE", "MED BOX", "LG DRUM", "JUMBO PACK"]
_MODES = ["MAIL", "SHIP", "AIR", "TRUCK", "RAIL", "FOB", "REG AIR"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]


def _gen_tpch(root):
    """Deterministic TPC-H-shaped tables."""
    rng = np.random.default_rng(22)
    day = lambda s: np.datetime64(s)

    def dates(base, spread, n):
        return (
            day(base) + rng.integers(0, spread, n).astype("timedelta64[D]")
        ).astype("datetime64[D]")

    region = pa.table(
        {
            "r_regionkey": pa.array(np.arange(N_REGION), pa.int64()),
            "r_name": pa.array(_REGIONS),
        }
    )
    nation_cols = {
        "n_nationkey": np.arange(N_NATION, dtype=np.int64),
        "n_name": _NATIONS,
        "n_regionkey": (np.arange(N_NATION) % N_REGION).astype(np.int64),
    }
    nation = pa.table(nation_cols)
    # pre-renamed copy for self-join queries (q7/q8/q9)
    nation2 = pa.table(
        {
            "n2_nationkey": nation_cols["n_nationkey"],
            "n2_name": nation_cols["n_name"],
            "n2_regionkey": nation_cols["n_regionkey"],
        }
    )
    supplier = pa.table(
        {
            "s_suppkey": pa.array(np.arange(N_SUPP), pa.int64()),
            "s_name": pa.array([f"Supplier#{i:09d}" for i in range(N_SUPP)]),
            "s_nationkey": pa.array(
                rng.integers(0, N_NATION, N_SUPP), pa.int64()
            ),
            "s_acctbal": pa.array(np.round(rng.uniform(-999, 9999, N_SUPP), 2)),
        }
    )
    customer = pa.table(
        {
            "c_custkey": pa.array(np.arange(N_CUST), pa.int64()),
            "c_name": pa.array([f"Customer#{i:09d}" for i in range(N_CUST)]),
            "c_nationkey": pa.array(
                rng.integers(0, N_NATION, N_CUST), pa.int64()
            ),
            "c_mktsegment": pa.array(
                [_SEGMENTS[i % len(_SEGMENTS)] for i in range(N_CUST)]
            ),
            "c_acctbal": pa.array(np.round(rng.uniform(-999, 9999, N_CUST), 2)),
        }
    )
    part = pa.table(
        {
            "p_partkey": pa.array(np.arange(N_PART), pa.int64()),
            "p_brand": pa.array(
                [_BRANDS[i % len(_BRANDS)] for i in range(N_PART)]
            ),
            "p_type": pa.array(
                [_TYPES[i % len(_TYPES)] for i in range(N_PART)]
            ),
            "p_size": pa.array(
                rng.integers(1, 51, N_PART), pa.int64()
            ),
            "p_container": pa.array(
                [_CONTAINERS[i % len(_CONTAINERS)] for i in range(N_PART)]
            ),
            "p_retailprice": pa.array(np.round(rng.uniform(900, 2000, N_PART), 2)),
        }
    )
    partsupp = pa.table(
        {
            "ps_partkey": pa.array(
                np.repeat(np.arange(N_PART), N_PARTSUPP // N_PART), pa.int64()
            ),
            "ps_suppkey": pa.array(
                rng.integers(0, N_SUPP, N_PARTSUPP), pa.int64()
            ),
            "ps_availqty": pa.array(
                rng.integers(1, 10000, N_PARTSUPP), pa.int64()
            ),
            "ps_supplycost": pa.array(
                np.round(rng.uniform(1, 1000, N_PARTSUPP), 2)
            ),
        }
    )
    orders = pa.table(
        {
            "o_orderkey": pa.array(np.arange(N_ORDERS), pa.int64()),
            "o_custkey": pa.array(
                rng.integers(0, N_CUST, N_ORDERS), pa.int64()
            ),
            "o_orderstatus": pa.array(
                [["O", "F", "P"][i % 3] for i in range(N_ORDERS)]
            ),
            "o_totalprice": pa.array(
                np.round(rng.uniform(1000, 450000, N_ORDERS), 2)
            ),
            "o_orderdate": pa.array(dates("1992-01-01", 2400, N_ORDERS)),
            "o_orderpriority": pa.array(
                [_PRIORITIES[i % len(_PRIORITIES)] for i in range(N_ORDERS)]
            ),
        }
    )
    ship = dates("1992-01-03", 2400, N_LINEITEM)
    commit = ship + rng.integers(-30, 60, N_LINEITEM).astype("timedelta64[D]")
    receipt = ship + rng.integers(1, 45, N_LINEITEM).astype("timedelta64[D]")
    lineitem = pa.table(
        {
            "l_orderkey": pa.array(
                rng.integers(0, N_ORDERS, N_LINEITEM), pa.int64()
            ),
            "l_partkey": pa.array(
                rng.integers(0, N_PART, N_LINEITEM), pa.int64()
            ),
            "l_suppkey": pa.array(
                rng.integers(0, N_SUPP, N_LINEITEM), pa.int64()
            ),
            "l_quantity": pa.array(
                rng.integers(1, 51, N_LINEITEM), pa.int64()
            ),
            "l_extendedprice": pa.array(
                np.round(rng.uniform(900, 100000, N_LINEITEM), 2)
            ),
            "l_discount": pa.array(
                np.round(rng.uniform(0.0, 0.1, N_LINEITEM), 2)
            ),
            "l_returnflag": pa.array(
                [["R", "A", "N"][i % 3] for i in range(N_LINEITEM)]
            ),
            "l_linestatus": pa.array(
                [["O", "F"][i % 2] for i in range(N_LINEITEM)]
            ),
            "l_shipdate": pa.array(ship),
            "l_commitdate": pa.array(commit.astype("datetime64[D]")),
            "l_receiptdate": pa.array(receipt.astype("datetime64[D]")),
            "l_shipmode": pa.array(
                [_MODES[i % len(_MODES)] for i in range(N_LINEITEM)]
            ),
        }
    )
    tables = {
        "region": (region, 1),
        "nation": (nation, 1),
        "nation2": (nation2, 1),
        "supplier": (supplier, 1),
        "customer": (customer, 2),
        "part": (part, 2),
        "partsupp": (partsupp, 2),
        "orders": (orders, 4),
        "lineitem": (lineitem, 4),
    }
    for name, (table, parts) in tables.items():
        d = os.path.join(root, name)
        os.makedirs(d)
        rows = table.num_rows
        for i in range(parts):
            lo, hi = i * rows // parts, (i + 1) * rows // parts
            pq.write_table(
                table.slice(lo, hi - lo), os.path.join(d, f"part-{i}.parquet")
            )
    return tables


@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    """One module-scoped dataset + session + index inventory (plan
    stability does not need the mesh-size matrix; queries still execute
    differentially)."""
    from hyperspace_tpu.session import HyperspaceSession

    root = str(tmp_path_factory.mktemp("tpch"))
    _gen_tpch(root)
    session = HyperspaceSession()
    session.conf.set(C.INDEX_SYSTEM_PATH, os.path.join(root, "_indexes"))
    session.conf.set(C.INDEX_NUM_BUCKETS, 4)
    session.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
    hs = Hyperspace(session)
    views = {}
    for name in (
        "region", "nation", "nation2", "supplier", "customer",
        "part", "partsupp", "orders", "lineitem",
    ):
        df = session.read.parquet(os.path.join(root, name))
        session.register_view(name, df)
        views[name] = df
    li, od, cu = views["lineitem"], views["orders"], views["customer"]
    pt, ps, sp = views["part"], views["partsupp"], views["supplier"]
    # fixed index inventory: join keys covered with the payload columns
    # the 22 queries project; MinMax sketches for the date-range scans
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_okey",
            ["l_orderkey"],
            ["l_quantity", "l_extendedprice", "l_shipdate", "l_commitdate",
             "l_receiptdate", "l_shipmode", "l_returnflag"],
        ),
    )
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_pkey",
            ["l_partkey"],
            ["l_quantity", "l_extendedprice", "l_shipdate"],
        ),
    )
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_skey",
            ["l_suppkey"],
            ["l_orderkey", "l_extendedprice", "l_shipdate",
             "l_receiptdate", "l_commitdate"],
        ),
    )
    hs.create_index(
        od,
        CoveringIndexConfig(
            "od_okey",
            ["o_orderkey"],
            ["o_custkey", "o_orderdate", "o_totalprice", "o_orderpriority",
             "o_orderstatus"],
        ),
    )
    hs.create_index(
        od,
        CoveringIndexConfig(
            "od_ckey",
            ["o_custkey"],
            ["o_orderkey", "o_orderdate", "o_totalprice"],
        ),
    )
    hs.create_index(
        cu,
        CoveringIndexConfig(
            "cu_ckey",
            ["c_custkey"],
            ["c_name", "c_nationkey", "c_mktsegment", "c_acctbal"],
        ),
    )
    hs.create_index(
        pt,
        CoveringIndexConfig(
            "pt_pkey",
            ["p_partkey"],
            ["p_brand", "p_type", "p_size", "p_container"],
        ),
    )
    hs.create_index(
        ps,
        CoveringIndexConfig(
            "ps_pkey", ["ps_partkey"], ["ps_suppkey", "ps_supplycost"]
        ),
    )
    hs.create_index(
        ps,
        CoveringIndexConfig(
            "ps_skey", ["ps_suppkey"], ["ps_partkey", "ps_supplycost"]
        ),
    )
    hs.create_index(
        sp,
        CoveringIndexConfig(
            "sp_skey", ["s_suppkey"], ["s_name", "s_nationkey", "s_acctbal"]
        ),
    )
    hs.create_index(
        li, DataSkippingIndexConfig("li_ship_sk", MinMaxSketch("l_shipdate"))
    )
    hs.create_index(
        od, DataSkippingIndexConfig("od_date_sk", MinMaxSketch("o_orderdate"))
    )
    session.enable_hyperspace()
    return {"session": session, "root": root}


QUERIES = {
    "q01": """
        SELECT l_returnflag, l_linestatus, SUM(l_quantity) AS sum_qty,
               SUM(l_extendedprice) AS sum_price, AVG(l_quantity) AS avg_qty,
               AVG(l_discount) AS avg_disc, COUNT(*) AS count_order
        FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus""",
    "q02": """
        SELECT s_acctbal, s_name, n_name, p_partkey
        FROM part
        JOIN partsupp ON p_partkey = ps_partkey
        JOIN supplier ON ps_suppkey = s_suppkey
        JOIN nation ON s_nationkey = n_nationkey
        JOIN region ON n_regionkey = r_regionkey
        WHERE p_size = 15 AND r_name = 'EUROPE'
        ORDER BY s_acctbal DESC, n_name, s_name, p_partkey LIMIT 100""",
    "q03": """
        SELECT o_orderkey, o_orderdate, SUM(l_extendedprice) AS revenue
        FROM customer
        JOIN orders ON c_custkey = o_custkey
        JOIN lineitem ON o_orderkey = l_orderkey
        WHERE c_mktsegment = 'BUILDING'
          AND o_orderdate < DATE '1995-03-15'
          AND l_shipdate > DATE '1995-03-15'
        GROUP BY o_orderkey, o_orderdate
        ORDER BY o_orderkey LIMIT 10""",
    "q04": """
        SELECT o_orderpriority, COUNT(*) AS order_count
        FROM orders
        JOIN lineitem ON o_orderkey = l_orderkey
        WHERE o_orderdate >= DATE '1993-07-01'
          AND o_orderdate < DATE '1993-10-01'
          AND l_commitdate < l_receiptdate
        GROUP BY o_orderpriority ORDER BY o_orderpriority""",
    "q05": """
        SELECT n_name, SUM(l_extendedprice) AS revenue
        FROM customer
        JOIN orders ON c_custkey = o_custkey
        JOIN lineitem ON o_orderkey = l_orderkey
        JOIN supplier ON l_suppkey = s_suppkey
        JOIN nation ON s_nationkey = n_nationkey
        JOIN region ON n_regionkey = r_regionkey
        WHERE r_name = 'ASIA'
          AND o_orderdate >= DATE '1994-01-01'
          AND o_orderdate < DATE '1995-01-01'
          AND c_nationkey = s_nationkey
        GROUP BY n_name ORDER BY n_name""",
    "q06": """
        SELECT SUM(l_extendedprice) AS revenue
        FROM lineitem
        WHERE l_shipdate >= DATE '1994-01-01'
          AND l_shipdate < DATE '1995-01-01'
          AND l_discount BETWEEN 0.05 AND 0.07
          AND l_quantity < 24""",
    "q07": """
        SELECT n_name, n2_name, SUM(l_extendedprice) AS revenue
        FROM supplier
        JOIN lineitem ON s_suppkey = l_suppkey
        JOIN orders ON l_orderkey = o_orderkey
        JOIN customer ON o_custkey = c_custkey
        JOIN nation ON s_nationkey = n_nationkey
        JOIN nation2 ON c_nationkey = n2_nationkey
        WHERE n_name = 'FRANCE' AND n2_name = 'GERMANY'
          AND l_shipdate >= DATE '1995-01-01'
          AND l_shipdate <= DATE '1996-12-31'
        GROUP BY n_name, n2_name""",
    "q08": """
        SELECT n2_name, SUM(l_extendedprice) AS volume
        FROM part
        JOIN lineitem ON p_partkey = l_partkey
        JOIN supplier ON l_suppkey = s_suppkey
        JOIN orders ON l_orderkey = o_orderkey
        JOIN customer ON o_custkey = c_custkey
        JOIN nation ON c_nationkey = n_nationkey
        JOIN region ON n_regionkey = r_regionkey
        JOIN nation2 ON s_nationkey = n2_nationkey
        WHERE r_name = 'AMERICA' AND p_type = 'ECONOMY'
          AND o_orderdate BETWEEN DATE '1995-01-01' AND DATE '1996-12-31'
        GROUP BY n2_name ORDER BY n2_name""",
    "q09": """
        SELECT n2_name, SUM(ps_supplycost) AS amount
        FROM part
        JOIN partsupp ON p_partkey = ps_partkey
        JOIN supplier ON ps_suppkey = s_suppkey
        JOIN nation2 ON s_nationkey = n2_nationkey
        WHERE p_type = 'STANDARD'
        GROUP BY n2_name ORDER BY n2_name""",
    "q10": """
        SELECT c_custkey, c_name, SUM(l_extendedprice) AS revenue
        FROM customer
        JOIN orders ON c_custkey = o_custkey
        JOIN lineitem ON o_orderkey = l_orderkey
        WHERE o_orderdate >= DATE '1993-10-01'
          AND o_orderdate < DATE '1994-01-01'
          AND l_returnflag = 'R'
        GROUP BY c_custkey, c_name ORDER BY c_custkey LIMIT 20""",
    "q11": """
        SELECT ps_partkey, SUM(ps_supplycost) AS value
        FROM partsupp
        JOIN supplier ON ps_suppkey = s_suppkey
        JOIN nation ON s_nationkey = n_nationkey
        WHERE n_name = 'GERMANY'
        GROUP BY ps_partkey ORDER BY ps_partkey LIMIT 50""",
    "q12": """
        SELECT l_shipmode, COUNT(*) AS line_count
        FROM orders
        JOIN lineitem ON o_orderkey = l_orderkey
        WHERE l_shipmode IN ('MAIL', 'SHIP')
          AND l_commitdate < l_receiptdate
          AND l_shipdate < l_commitdate
          AND l_receiptdate >= DATE '1994-01-01'
          AND l_receiptdate < DATE '1995-01-01'
        GROUP BY l_shipmode ORDER BY l_shipmode""",
    "q13": """
        SELECT c_custkey, COUNT(*) AS c_count
        FROM customer
        JOIN orders ON c_custkey = o_custkey
        GROUP BY c_custkey ORDER BY c_custkey LIMIT 100""",
    "q14": """
        SELECT SUM(l_extendedprice) AS promo_revenue
        FROM lineitem
        JOIN part ON l_partkey = p_partkey
        WHERE l_shipdate >= DATE '1995-09-01'
          AND l_shipdate < DATE '1995-10-01'
          AND p_type = 'PROMO'""",
    "q15": """
        SELECT l_suppkey, SUM(l_extendedprice) AS total_revenue
        FROM lineitem
        WHERE l_shipdate >= DATE '1996-01-01'
          AND l_shipdate < DATE '1996-04-01'
        GROUP BY l_suppkey ORDER BY l_suppkey LIMIT 10""",
    "q16": """
        SELECT p_brand, p_type, p_size, COUNT(*) AS supplier_cnt
        FROM partsupp
        JOIN part ON ps_partkey = p_partkey
        WHERE p_brand <> 'Brand#45'
          AND p_size IN (49, 14, 23, 45, 19, 3, 36, 9)
        GROUP BY p_brand, p_type, p_size
        ORDER BY p_brand, p_type, p_size LIMIT 40""",
    "q17": """
        SELECT SUM(l_extendedprice) AS avg_yearly
        FROM lineitem
        JOIN part ON l_partkey = p_partkey
        WHERE p_brand = 'Brand#23' AND p_container = 'MED BOX'
          AND l_quantity < 5""",
    "q18": """
        SELECT c_custkey, o_orderkey, SUM(l_quantity) AS total_qty
        FROM customer
        JOIN orders ON c_custkey = o_custkey
        JOIN lineitem ON o_orderkey = l_orderkey
        WHERE o_totalprice > 400000
        GROUP BY c_custkey, o_orderkey ORDER BY o_orderkey LIMIT 100""",
    "q19": """
        SELECT SUM(l_extendedprice) AS revenue
        FROM lineitem
        JOIN part ON l_partkey = p_partkey
        WHERE (p_brand = 'Brand#12' AND l_quantity BETWEEN 1 AND 11)
           OR (p_brand = 'Brand#23' AND l_quantity BETWEEN 10 AND 20)
           OR (p_brand = 'Brand#34' AND l_quantity BETWEEN 20 AND 30)""",
    "q20": """
        SELECT s_name, s_acctbal
        FROM supplier
        JOIN nation ON s_nationkey = n_nationkey
        WHERE n_name = 'CANADA'
        ORDER BY s_name""",
    "q21": """
        SELECT s_name, COUNT(*) AS numwait
        FROM supplier
        JOIN lineitem ON s_suppkey = l_suppkey
        JOIN orders ON l_orderkey = o_orderkey
        JOIN nation ON s_nationkey = n_nationkey
        WHERE o_orderstatus = 'F'
          AND l_receiptdate > l_commitdate
          AND n_name = 'SAUDI ARABIA'
        GROUP BY s_name ORDER BY s_name LIMIT 100""",
    "q22": """
        SELECT c_mktsegment, COUNT(*) AS numcust, SUM(c_acctbal) AS totacctbal
        FROM customer
        WHERE c_acctbal > 7000
          AND c_mktsegment IN ('BUILDING', 'MACHINERY', 'AUTOMOBILE')
        GROUP BY c_mktsegment ORDER BY c_mktsegment""",
}


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_tpch_plan_stability(qname, tpch):
    from golden_utils import check_or_generate, simplify_plan

    session = tpch["session"]
    root = tpch["root"]
    df = session.sql(QUERIES[qname])
    with_idx_plan = simplify_plan(
        session.optimize(df.logical_plan).pretty(), root
    )
    session.disable_hyperspace()
    try:
        raw_plan = simplify_plan(
            session.optimize(df.logical_plan).pretty(), root
        )
    finally:
        session.enable_hyperspace()
    got = (
        "=== with indexes ===\n" + with_idx_plan + "\n"
        "=== without indexes ===\n" + raw_plan + "\n"
    )
    golden_path = os.path.join(GOLDEN_DIR, f"{qname}.txt")
    if check_or_generate(golden_path, got, qname):
        pytest.skip("golden file regenerated")
    # differential execution: indexed answer == unindexed answer.
    # Float SUM/AVG aggregates are compared with tolerance — the index
    # path feeds rows to the reduction in a different order and double
    # addition is not associative (exact for every other type).
    with_idx = df.collect()
    session.disable_hyperspace()
    try:
        base = df.collect()
    finally:
        session.enable_hyperspace()
    key = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
    a, b = key(with_idx), key(base)
    assert a.num_rows == b.num_rows and a.column_names == b.column_names, qname
    for col in a.column_names:
        av, bv = a.column(col), b.column(col)
        if pa.types.is_floating(av.type):
            assert np.allclose(
                av.to_numpy(zero_copy_only=False),
                bv.to_numpy(zero_copy_only=False),
                rtol=1e-9,
                equal_nan=True,
            ), (qname, col)
        else:
            assert av.equals(bv), (qname, col)


def test_corpus_is_complete():
    assert len(QUERIES) == 22
    assert sorted(QUERIES) == [f"q{i:02d}" for i in range(1, 23)]
