"""explain / why_not smoke + behavior tests.

Mirrors ``plananalysis/ExplainTest.scala`` (plan-diff rendering) and the
``CandidateIndexAnalyzer`` whyNot report: the APIs must return non-trivial
strings, name the indexes used, and surface recorded FilterReasons.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig


@pytest.fixture
def hs(session):
    return Hyperspace(session)


@pytest.fixture
def df(session, sample_parquet):
    return session.read.parquet(sample_parquet)


class TestExplain:
    def test_explain_shows_used_index_and_diff(self, session, hs, df):
        hs.create_index(df, CoveringIndexConfig("cl_idx", ["clicks"], ["query"]))
        q = df.filter(df["clicks"] == 100).select("query")
        out = hs.explain(q)
        assert "Plan with indexes:" in out
        assert "Plan without indexes:" in out
        assert "Indexes used:" in out
        assert "cl_idx" in out
        assert "<----" in out  # changed scan highlighted
        # with-index section scans the index, without-index scans parquet
        with_part = out.split("Plan without indexes:")[0]
        assert "Hyperspace(Type: CI, Name: cl_idx" in with_part

    def test_explain_no_index_used(self, session, hs, df):
        hs.create_index(df, CoveringIndexConfig("cl_idx", ["clicks"], ["query"]))
        # predicate on a non-indexed column -> no rewrite
        q = df.filter(df["imprs"] == 5).select("date")
        out = hs.explain(q)
        assert "(none)" in out.split("Indexes used:")[1]

    def test_explain_verbose_operator_diff(self, session, hs, df):
        hs.create_index(df, CoveringIndexConfig("cl_idx", ["clicks"], ["query"]))
        q = df.filter(df["clicks"] == 100).select("query")
        out = hs.explain(q, verbose=True)
        assert "Operator diff:" in out
        assert "Applicable indexes:" in out
        assert "cl_idx: kind=CoveringIndex" in out

    def test_explain_does_not_toggle_session_state(self, session, hs, df):
        hs.create_index(df, CoveringIndexConfig("cl_idx", ["clicks"], ["query"]))
        session.disable_hyperspace()
        hs.explain(df.filter(df["clicks"] == 100).select("query"))
        assert not session.is_hyperspace_enabled()
        session.enable_hyperspace()
        hs.explain(df.filter(df["clicks"] == 100).select("query"))
        assert session.is_hyperspace_enabled()


class TestDisplayModes:
    def _q(self, hs, df):
        hs.create_index(df, CoveringIndexConfig("dm_idx", ["clicks"], ["query"]))
        return df.filter(df["clicks"] >= 100).select("clicks", "query")

    def test_console_mode_ansi_highlight(self, session, hs, df):
        q = self._q(hs, df)
        out = hs.explain(q, mode="console")
        assert "\x1b[93m" in out and "\x1b[0m" in out
        assert "dm_idx" in out

    def test_html_mode_escapes_and_bolds(self, session, hs, df):
        q = self._q(hs, df)
        out = hs.explain(q, mode="html")
        assert "<b>" in out and "</b>" in out and "<br/>" in out
        # plan text angle brackets are escaped, tags are not
        assert "&gt;=" in out  # the >= in the filter condition

    def test_mode_from_conf(self, session, hs, df):
        q = self._q(hs, df)
        session.conf.set(C.EXPLAIN_DISPLAY_MODE, "console")
        assert "\x1b[93m" in hs.explain(q)

    def test_unknown_mode_rejected(self, session, hs, df):
        from hyperspace_tpu.exceptions import HyperspaceException

        q = self._q(hs, df)
        with pytest.raises(HyperspaceException, match="display mode"):
            hs.explain(q, mode="nope")

    def test_explain_golden(self, session, hs, df, sample_parquet):
        """Golden-file protection for the explain output format
        (reference: per-version expected/*.txt fixtures, ExplainTest)."""
        import os
        import re

        q = self._q(hs, df)
        out = hs.explain(q)
        norm = out.replace(sample_parquet, "<src>")
        norm = re.sub(r"LogVersion: \d+", "LogVersion: N", norm)
        norm = re.sub(r"\(v\d+\): \S+", "(vN): <index-path>", norm)
        golden = os.path.join(
            os.path.dirname(__file__), "goldstandard", "explain_filter.txt"
        )
        if os.environ.get("HS_GENERATE_GOLDEN_FILES") == "1":
            with open(golden, "w") as f:
                f.write(norm)
            pytest.skip("golden regenerated")
        with open(golden) as f:
            assert norm == f.read()


class TestWhyNotGolden:
    def test_why_not_golden(self, session, hs, df, sample_parquet):
        """Golden-file protection for the why_not report format (the
        reference pins whyNot_* fixtures per version)."""
        import os
        import re

        hs.create_index(df, CoveringIndexConfig("wn_idx", ["clicks"], ["query"]))
        session.enable_hyperspace()
        # predicate on a non-first-indexed column: index NOT applicable
        q = df.filter(df["query"] == "banana").select("query", "imprs")
        out = hs.why_not(q)
        norm = out.replace(sample_parquet, "<src>")
        norm = re.sub(r"LogVersion: \d+", "LogVersion: N", norm)
        golden = os.path.join(
            os.path.dirname(__file__), "goldstandard", "why_not_filter.txt"
        )
        if os.environ.get("HS_GENERATE_GOLDEN_FILES") == "1":
            with open(golden, "w") as f:
                f.write(norm)
            pytest.skip("golden regenerated")
        with open(golden) as f:
            assert norm == f.read()


class TestProfilerIntegration:
    def test_trace_dir_produces_trace(self, session, df, tmp_path):
        trace_dir = str(tmp_path / "trace")
        session.conf.set(C.PROFILE_TRACE_DIR, trace_dir)
        df.filter(df["clicks"] >= 100).select("clicks").collect()
        session.conf.set(C.PROFILE_TRACE_DIR, "")
        import os

        found = []
        for root, _dirs, files in os.walk(trace_dir):
            found.extend(files)
        assert found, "no profiler trace files written"


class TestWhyNot:
    def test_why_not_reports_reasons(self, session, hs, df):
        hs.create_index(df, CoveringIndexConfig("cl_idx", ["clicks"], ["query"]))
        # imprs is not covered -> MISSING_REQUIRED_COL (or no-first-col)
        q = df.filter(df["clicks"] == 100).select("imprs")
        out = hs.why_not(q)
        assert "Non-applicable indexes:" in out
        assert "cl_idx" in out
        assert "MISSING_REQUIRED_COL" in out

    def test_why_not_applied_index_listed_applicable(self, session, hs, df):
        hs.create_index(df, CoveringIndexConfig("cl_idx", ["clicks"], ["query"]))
        q = df.filter(df["clicks"] == 100).select("query")
        out = hs.why_not(q)
        assert "cl_idx: applied" in out

    def test_why_not_first_indexed_col_reason(self, session, hs, df):
        hs.create_index(
            df, CoveringIndexConfig("iq_idx", ["imprs", "clicks"], ["query"])
        )
        q = df.filter(df["clicks"] == 100).select("query")
        out = hs.why_not(q, extended=True)
        assert "NO_FIRST_INDEXED_COL_COND" in out
        assert "first indexed column" in out  # verbose text in extended mode

    def test_why_not_named_index_filter(self, session, hs, df):
        hs.create_index(df, CoveringIndexConfig("cl_idx", ["clicks"], ["query"]))
        q = df.filter(df["clicks"] == 100).select("imprs")
        out = hs.why_not(q, index_name="cl_idx")
        assert "cl_idx" in out
        from hyperspace_tpu.exceptions import HyperspaceException

        with pytest.raises(HyperspaceException, match="No ACTIVE index"):
            hs.why_not(q, index_name="nope")

    def test_why_not_source_changed_reason(self, session, hs, df, sample_parquet):
        import os

        hs.create_index(df, CoveringIndexConfig("cl_idx", ["clicks"], ["query"]))
        # append a new file -> exact-mode signature mismatch
        t = pa.table(
            {
                "date": ["2018-01-01"],
                "rguid": ["g"],
                "clicks": pa.array([1], type=pa.int64()),
                "query": ["zzz"],
                "imprs": pa.array([2], type=pa.int64()),
            }
        )
        pq.write_table(t, os.path.join(sample_parquet, "extra.parquet"))
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = df2.filter(df2["clicks"] == 100).select("query")
        out = hs.why_not(q)
        assert "SOURCE_DATA_CHANGED" in out

    def test_why_not_reasons_do_not_accumulate(self, session, hs, df):
        hs.create_index(df, CoveringIndexConfig("cl_idx", ["clicks"], ["query"]))
        q = df.filter(df["clicks"] == 100).select("imprs")
        out1 = hs.why_not(q)
        out2 = hs.why_not(q)
        assert out1.count("MISSING_REQUIRED_COL") == out2.count(
            "MISSING_REQUIRED_COL"
        )
