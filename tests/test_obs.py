"""Observability plane (hyperspace_tpu/obs/, docs/observability.md).

Four legs, mirroring the ISSUE's acceptance criteria:

* span propagation: a pipelined join serve's stage spans (recorded on
  scan-pool and per-bucket-pool worker threads) attach to the query's
  root span, and parent-child integrity holds under a concurrent
  client storm;
* metrics exact-accounting: the registry's live views ARE the
  frontend/cache ``stats()`` dicts and the breakdown instruments ARE
  ``last_serve_breakdown`` — one storage, never a fork;
* trace linkage across the fleet claim/spool plane: a cross-process
  single-flight loser's root span records the winner's trace id;
* querylog: one row per executed query, schema-valid, replayable
  (rotation + crash-mid-rotate recovery live in
  ``tests/test_crash_recovery.py::TestQuerylogRotateCrash``).
"""

import os
import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.obs import merge_snapshots, metrics, querylog, trace
from hyperspace_tpu.serve.frontend import ServeFrontend


@pytest.fixture(autouse=True)
def _obs_isolation():
    """Tracing is a process-global switch: leave it OFF and the ring
    empty for whatever test runs next."""
    trace.reset()
    yield
    trace.set_enabled(False)
    trace.reset()


def _lake(tmp_path, n=20_000, n_orders=2_000):
    rng = np.random.default_rng(23)
    idir, odir = tmp_path / "items", tmp_path / "orders"
    idir.mkdir()
    odir.mkdir()
    items = pa.table(
        {
            "k": rng.integers(0, n_orders, n).astype(np.int64),
            "q": rng.integers(1, 51, n).astype(np.int64),
        }
    )
    orders = pa.table(
        {
            "ok": np.arange(n_orders, dtype=np.int64),
            "cust": rng.integers(0, 500, n_orders).astype(np.int64),
        }
    )
    for i in range(4):
        lo, hi = i * n // 4, (i + 1) * n // 4
        pq.write_table(items.slice(lo, hi - lo), str(idir / f"p{i}.parquet"))
        lo, hi = i * n_orders // 4, (i + 1) * n_orders // 4
        pq.write_table(orders.slice(lo, hi - lo), str(odir / f"p{i}.parquet"))
    return str(idir), str(odir)


@pytest.fixture
def obs_env(session_factory, tmp_path):
    """One obs-enabled session over an indexed two-table lake."""
    s = session_factory(1)
    idir, odir = _lake(tmp_path)
    hs = Hyperspace(s)
    items = s.read.parquet(idir)
    orders = s.read.parquet(odir)
    hs.create_index(items, CoveringIndexConfig("oi1", ["k"], ["q"]))
    hs.create_index(orders, CoveringIndexConfig("oo1", ["ok"], ["cust"]))
    s.enable_hyperspace()
    s.conf.set(C.OBS_ENABLED, True)
    return {"s": s, "hs": hs, "items": items, "orders": orders,
            "idir": idir, "odir": odir}


def _assert_trace_integrity(root):
    """Every recorded span belongs to the root's trace and its parent
    chain terminates at the root."""
    by_id = {sp.span_id: sp for sp in root.spans}
    by_id[root.span_id] = root
    for sp in root.spans:
        assert sp.trace_id == root.trace_id, (sp.name, sp.trace_id)
        if sp is root:
            continue
        assert sp.parent_id in by_id, (sp.name, sp.parent_id)
        hops, cur = 0, sp
        while cur is not root:
            cur = by_id[cur.parent_id]
            hops += 1
            assert hops < 100, "parent cycle"
        assert sp.duration_s is not None and sp.duration_s >= 0.0


# ---------------------------------------------------------------------------
# Trace core
# ---------------------------------------------------------------------------


class TestTraceCore:
    def test_disabled_is_noop(self):
        trace.set_enabled(False)
        assert trace.root("serve.query") is trace.NOOP
        with trace.span("scan") as sp:
            assert sp is trace.NOOP
        trace.stage("scan", 0.0)
        assert trace.finished() == []
        assert trace.current_trace_id() is None

    def test_root_child_shape(self):
        trace.set_enabled(True)
        root = trace.root("serve.query", slo_class="t")
        with trace.activate(root):
            with trace.span("pin"):
                pass
            trace.stage("scan", seconds=0.25)
            trace.event("retry", attempt=2)
        root.finish()
        roots = trace.finished("serve.query")
        assert len(roots) == 1
        _assert_trace_integrity(roots[0])
        stages = roots[0].stage_seconds()
        assert set(stages) == {"pin", "scan"}
        assert abs(stages["scan"] - 0.25) < 0.02
        assert roots[0].events[0]["name"] == "retry"
        assert roots[0].attrs["slo_class"] == "t"

    def test_finish_idempotent_and_span_cap(self):
        trace.set_enabled(True)
        import hyperspace_tpu.obs.trace as tr

        old = tr._max_spans
        tr._max_spans = 3
        try:
            root = trace.root("serve.query")
            with trace.activate(root):
                for _ in range(10):
                    with trace.span("scan"):
                        pass
            root.finish()
            root.finish()  # idempotent
            assert len(trace.finished()) == 1
            assert len(root.spans) == 3
            assert root.spans_dropped > 0
        finally:
            tr._max_spans = old

    def test_carry_propagates_across_pool(self):
        from concurrent.futures import ThreadPoolExecutor

        trace.set_enabled(True)
        root = trace.root("serve.query")
        with trace.activate(root):
            with ThreadPoolExecutor(max_workers=4) as pool:
                # carried: records under the root from worker threads
                list(
                    pool.map(
                        trace.carry(lambda i: trace.stage("scan", 0.0)),
                        range(8),
                    )
                )
                # NOT carried: context does not leak to pool threads
                def bare(i):
                    assert trace.current() is None
                    return i

                list(pool.map(bare, range(4)))
        root.finish()
        _assert_trace_integrity(root)
        assert len([s for s in root.spans if s.name == "scan"]) == 8

    def test_ring_bounded_by_retain(self):
        trace.set_enabled(True)
        import hyperspace_tpu.obs.trace as tr

        with tr._rec_lock:
            old = tr._finished.maxlen
        from collections import deque

        with tr._rec_lock:
            tr._finished = deque(maxlen=5)
        try:
            for _ in range(12):
                trace.root("serve.query").finish()
            assert len(trace.finished()) == 5
        finally:
            with tr._rec_lock:
                tr._finished = deque(maxlen=old)


# ---------------------------------------------------------------------------
# merge_snapshots (the one documented counter-merge helper)
# ---------------------------------------------------------------------------


class TestMergeSnapshots:
    def test_sum_max_drop_semantics(self):
        a = {
            "completed": 3,
            "p50_ms": 10.0,
            "snapshot_at_ms": 100,
            "high_water_bytes": 50,
            "max_bytes": 100,
            "fleet": {"spool_hits": 1},
            "name": "a",
        }
        b = {
            "completed": 4,
            "p50_ms": 99.0,
            "snapshot_at_ms": 200,
            "high_water_bytes": 70,
            "max_bytes": 100,
            "fleet": {"spool_hits": 2},
            "name": "b",
        }
        m = merge_snapshots(a, b)
        assert m["completed"] == 7  # counters sum
        assert "p50_ms" not in m  # percentiles do not merge
        assert m["snapshot_at_ms"] == 200  # stamps take the max
        assert m["high_water_bytes"] == 70  # watermarks take the max
        assert m["max_bytes"] == 100
        assert m["fleet"]["spool_hits"] == 3  # nested dicts merge
        assert m["name"] == "a"  # non-numeric keeps first

    def test_empty_and_non_dict_tolerated(self):
        assert merge_snapshots() == {}
        assert merge_snapshots({}, None, {"x": 1}) == {"x": 1}


# ---------------------------------------------------------------------------
# Serve-path spans: propagation through scan/prepare pools
# ---------------------------------------------------------------------------


class TestServeSpans:
    def test_one_root_per_query_with_stage_children(self, obs_env):
        s, items = obs_env["s"], obs_env["items"]
        fe = ServeFrontend(s)
        try:
            q = items.filter(items["k"] == 7).select("k", "q")
            out = fe.serve(q)
        finally:
            fe.close()
        roots = trace.finished("serve.query")
        assert len(roots) == 1
        root = roots[0]
        _assert_trace_integrity(root)
        stages = root.stage_seconds()
        assert "queue_wait" in stages
        assert "pin" in stages
        assert "execute" in stages
        assert root.attrs["status"] == "ok"
        assert root.attrs["rows_returned"] == out.num_rows
        assert root.attrs["fingerprint"]
        assert root.attrs["indexes"] == ["oi1"]
        assert root.attrs["rule"] == "filter"
        # predicate shape is literal-scrubbed
        assert "7" not in root.attrs["predicate"].replace("int64", "")

    def test_join_spans_cross_scan_pool(self, obs_env):
        """The pipelined join's scan/prepare/match stages record on
        scan-pool and per-bucket-pool worker threads; trace.carry must
        hand them the root context — the breakdown keys and the span
        names are the same taxonomy."""
        from hyperspace_tpu.execution import join_exec

        s, items, orders = obs_env["s"], obs_env["items"], obs_env["orders"]
        fe = ServeFrontend(s)
        try:
            q = orders.join(items, on=orders["ok"] == items["k"]).select(
                "ok", "cust", "q"
            )
            fe.serve(q)
        finally:
            fe.close()
        roots = trace.finished("serve.query")
        assert len(roots) == 1
        root = roots[0]
        _assert_trace_integrity(root)
        stages = root.stage_seconds()
        for want in ("scan", "prepare", "match", "expand", "assemble"):
            assert want in stages, (want, sorted(stages))
        # span timings and the legacy breakdown are the SAME measurement
        # (this was the only query since the executor's reset)
        bd = dict(join_exec.last_serve_breakdown)
        for stage_name, sec in bd.items():
            assert stage_name in stages, stage_name
            assert abs(stages[stage_name] - sec) < 0.05, (stage_name, sec)
        assert root.attrs["rule"] == "join"
        assert set(root.attrs["indexes"]) == {"oi1", "oo1"}

    def test_obs_off_bit_identical_and_traceless(self, obs_env):
        s, items = obs_env["s"], obs_env["items"]
        q = items.filter(items["k"] == 9).select("k", "q")
        fe = ServeFrontend(s)
        try:
            with_obs = fe.serve(q)
        finally:
            fe.close()
        s.conf.set(C.OBS_ENABLED, False)
        trace.reset()
        fe2 = ServeFrontend(s)
        try:
            without = fe2.serve(q)
        finally:
            fe2.close()
        assert with_obs.equals(without)
        assert trace.finished() == []

    def test_concurrent_parent_child_integrity(self, obs_env):
        """16 clients x 4 distinct queries each: every trace's spans
        chain to ITS root (no cross-trace leakage through the shared
        scan pool), and roots == executions (dedup shares a trace)."""
        s, items = obs_env["s"], obs_env["items"]
        s.conf.set(C.SERVE_MAX_QUEUE_DEPTH, 0)
        fe = ServeFrontend(s)
        errors = []
        try:
            def client(ci):
                try:
                    for j in range(4):
                        k = (ci * 17 + j * 5) % 200
                        q = items.filter(items["k"] == k).select("k", "q")
                        fe.serve(q)
                except Exception as exc:  # pragma: no cover
                    errors.append(exc)

            threads = [
                threading.Thread(target=client, args=(ci,))
                for ci in range(16)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            stats = fe.stats()
        finally:
            fe.close()
        assert not errors, errors[:3]
        roots = trace.finished("serve.query")
        assert len(roots) == stats["completed"]
        assert stats["completed"] + stats["deduped"] == stats["admitted"]
        seen_trace_ids = set()
        for root in roots:
            _assert_trace_integrity(root)
            assert root.trace_id not in seen_trace_ids
            seen_trace_ids.add(root.trace_id)


# ---------------------------------------------------------------------------
# Metrics: views ARE the stats, instruments ARE the breakdowns
# ---------------------------------------------------------------------------


class TestMetricsAccounting:
    def test_frontend_view_is_stats(self, obs_env):
        s, items = obs_env["s"], obs_env["items"]
        fe = ServeFrontend(s)
        try:
            for k in (1, 2, 3):
                fe.serve(items.filter(items["k"] == k).select("k"))
            snap = metrics.registry.snapshot()
            direct = fe.stats()
            view = snap["views"]["serve_frontend"]
            for key in ("admitted", "completed", "deduped", "shed",
                        "retries", "degraded", "failed"):
                assert view[key] == direct[key], key
            assert direct["completed"] == 3
            assert "snapshot_at_ms" in direct
        finally:
            fe.close()
        # closed frontends unregister; the exporter must not fail
        assert "serve_frontend" not in metrics.registry.snapshot()["views"]

    def test_breakdown_is_registry_instrument(self, obs_env):
        from hyperspace_tpu.execution import join_exec
        from hyperspace_tpu.indexes import covering_build

        inst = metrics.registry.stage_timer("hs_serve_stage_seconds")
        assert inst.data is join_exec.last_serve_breakdown
        binst = metrics.registry.stage_timer("hs_build_stage_seconds")
        assert binst.data is covering_build.last_build_breakdown
        s, items, orders = obs_env["s"], obs_env["items"], obs_env["orders"]
        fe = ServeFrontend(s)
        try:
            fe.serve(orders.join(items, on=orders["ok"] == items["k"]))
        finally:
            fe.close()
        assert inst.snapshot() == dict(join_exec.last_serve_breakdown)
        assert inst.snapshot(), "join recorded no stages"

    def test_serve_cache_view_live(self, obs_env):
        s = obs_env["s"]
        s.conf.set(C.SERVE_CACHE_ENABLED, True)
        cache = s.serve_cache
        assert cache is not None
        snap = metrics.registry.snapshot()["views"]["serve_cache"]
        assert snap == cache.stats() or (
            # snapshot_at_ms may tick between the two reads
            {k: v for k, v in snap.items() if k != "snapshot_at_ms"}
            == {
                k: v
                for k, v in cache.stats().items()
                if k != "snapshot_at_ms"
            }
        )

    def test_prometheus_render_contains_instruments(self, obs_env):
        s, items = obs_env["s"], obs_env["items"]
        fe = ServeFrontend(s)
        try:
            fe.serve(items.filter(items["k"] == 5).select("k"))
            text = metrics.registry.render_prometheus()
        finally:
            fe.close()
        assert "# TYPE hs_obs_traces_total counter" in text
        assert "hs_view_serve_frontend" in text
        assert 'key="completed"' in text

    def test_events_counter_and_emit_time_stamp(self, obs_env):
        from hyperspace_tpu import telemetry as T

        s = obs_env["s"]
        before = metrics.events_total.snapshot().get("CreateActionEvent", 0)
        ev = T.CreateActionEvent(index_name="x")
        assert ev.timestamp_ms == 0  # NOT stamped at construction
        s.event_logging.log_event(ev)
        assert ev.timestamp_ms > 0  # stamped at emit
        after = metrics.events_total.snapshot().get("CreateActionEvent", 0)
        assert after == before + 1

    def test_jsonl_event_logger_writes(self, obs_env, tmp_path):
        from hyperspace_tpu import telemetry as T

        s = obs_env["s"]
        path = str(tmp_path / "events.jsonl")
        s.conf.set(C.OBS_EVENTLOG_PATH, path)
        s.conf.set(
            C.EVENT_LOGGER_CLASS,
            "hyperspace_tpu.telemetry.JsonlEventLogger",
        )
        s.event_logging.log_event(T.RefreshActionEvent(index_name="idx"))
        s.event_logging.log_event(T.VacuumActionEvent(index_name="idx"))
        recs = metrics.read_jsonl(path)
        assert [r["event"] for r in recs] == [
            "RefreshActionEvent",
            "VacuumActionEvent",
        ]
        assert all(r["timestamp_ms"] > 0 for r in recs)
        assert recs[0]["index_name"] == "idx"


# ---------------------------------------------------------------------------
# Lifecycle action spans
# ---------------------------------------------------------------------------


class TestActionSpans:
    def test_create_action_root_with_build_stages(
        self, session_factory, tmp_path
    ):
        s = session_factory(1)
        idir, _odir = _lake(tmp_path)
        s.conf.set(C.OBS_ENABLED, True)
        hs = Hyperspace(s)
        items = s.read.parquet(idir)
        hs.create_index(items, CoveringIndexConfig("ai1", ["k"], ["q"]))
        roots = trace.finished("action.CreateAction")
        assert len(roots) == 1
        root = roots[0]
        _assert_trace_integrity(root)
        assert root.attrs["status"] == "ok"
        assert root.attrs["index"] == "ai1"
        stages = root.stage_seconds()
        for want in ("scan", "sort", "write", "log_commit"):
            assert want in stages, (want, sorted(stages))
        # the build breakdown and the spans are one measurement
        from hyperspace_tpu.indexes import covering_build

        for name, sec in covering_build.last_build_breakdown.items():
            if name in ("tail_wall", "tail_shards"):
                continue  # derived values, not _stage_add increments
            assert name in stages, name

    def test_failed_action_still_finishes_root(
        self, session_factory, tmp_path
    ):
        from hyperspace_tpu.exceptions import HyperspaceException

        s = session_factory(1)
        idir, _ = _lake(tmp_path)
        s.conf.set(C.OBS_ENABLED, True)
        hs = Hyperspace(s)
        items = s.read.parquet(idir)
        hs.create_index(items, CoveringIndexConfig("dup", ["k"], ["q"]))
        trace.reset()
        with pytest.raises(HyperspaceException):
            hs.create_index(items, CoveringIndexConfig("dup", ["k"], ["q"]))
        roots = trace.finished("action.CreateAction")
        assert len(roots) == 1
        assert roots[0].attrs["status"] == "failed"


# ---------------------------------------------------------------------------
# Querylog: one row per execution
# ---------------------------------------------------------------------------


class TestQuerylogIntegration:
    def test_row_per_execution_and_schema(self, obs_env):
        s, items = obs_env["s"], obs_env["items"]
        fe = ServeFrontend(s)
        try:
            for k in (11, 12, 13, 11):
                fe.serve(items.filter(items["k"] == k).select("k", "q"))
            completed = fe.stats()["completed"]
        finally:
            fe.close()
        records = querylog.read_records(querylog.obs_root(s.conf))
        assert len(records) == completed
        fps = set()
        for r in records:
            assert querylog.validate_record(r) is None, r
            assert r["trace_id"]
            assert r["stages"].get("execute", 0) >= 0
            assert r["indexes"] == ["oi1"]
            fps.add(r["fingerprint"])
        # k=11 served twice -> same fingerprint; 3 distinct literals
        assert len(fps) == 3
        shapes = {r["predicate"] for r in records}
        assert len(shapes) == 1, "literal scrubbing failed"
        # the rows replay against the trace ring
        ring = {t.trace_id for t in trace.finished("serve.query")}
        assert {r["trace_id"] for r in records} <= ring

    def test_querylog_disabled_writes_nothing(self, obs_env):
        s, items = obs_env["s"], obs_env["items"]
        s.conf.set(C.OBS_QUERYLOG_ENABLED, False)
        fe = ServeFrontend(s)
        try:
            fe.serve(items.filter(items["k"] == 3).select("k"))
        finally:
            fe.close()
        assert querylog.read_records(querylog.obs_root(s.conf)) == []


# ---------------------------------------------------------------------------
# Fleet: trace linkage through the claim/spool plane
# ---------------------------------------------------------------------------


class TestFleetTraceLinkage:
    def test_spool_hit_links_winner_trace(self, session_factory, tmp_path):
        """Two in-process FleetFrontends (separate sessions, shared
        lake — the same stand-in tests/test_fleet.py uses): the loser
        serving from the winner's spooled result records a spool_hit
        event carrying the WINNER's trace id."""
        from hyperspace_tpu.session import HyperspaceSession

        src = tmp_path / "src"
        src.mkdir()
        rng = np.random.default_rng(5)
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(
                        rng.integers(0, 50, 3000), pa.int64()
                    ),
                    "v": pa.array(
                        rng.integers(0, 100, 3000), pa.int64()
                    ),
                }
            ),
            str(src / "p0.parquet"),
        )
        index_root = str(tmp_path / "indexes")

        def make_session():
            s = HyperspaceSession()
            s.conf.set(C.INDEX_SYSTEM_PATH, index_root)
            s.conf.set(C.INDEX_NUM_BUCKETS, 4)
            s.conf.set(C.FLEET_ENABLED, True)
            # this test witnesses the DURABLE claim/spool trace linkage;
            # the fast plane would turn the second serve into a routed
            # owner handoff and elect nobody
            s.conf.set(C.FLEET_FAST_ENABLED, False)
            s.conf.set(C.OBS_ENABLED, True)
            s.enable_hyperspace()
            return s

        s1 = make_session()
        hs1 = Hyperspace(s1)
        df = s1.read.parquet(str(src))
        hs1.create_index(df, CoveringIndexConfig("fl1", ["k"], ["v"]))
        trace.reset()
        s2 = make_session()
        fe1, fe2 = s1.serve_frontend, s2.serve_frontend
        try:
            q1 = s1.read.parquet(str(src))
            q1 = q1.filter(q1["k"] == 9)
            q2 = s2.read.parquet(str(src))
            q2 = q2.filter(q2["k"] == 9)
            t1 = fe1.serve(q1)
            t2 = fe2.serve(q2)
            assert t1.sort_by("v").equals(t2.sort_by("v"))
            st1, st2 = fe1.stats()["fleet"], fe2.stats()["fleet"]
            assert st1["claims_won"] + st2["claims_won"] == 1
            assert st1["spool_hits"] + st2["spool_hits"] == 1
        finally:
            fe1.close()
            fe2.close()
        roots = trace.finished("serve.query")
        assert len(roots) == 2
        winner = next(
            r for r in roots
            if any(e["name"] == "singleflight_won" for e in r.events)
        )
        loser = next(r for r in roots if r is not winner)
        hits = [e for e in loser.events if e["name"] == "spool_hit"]
        assert hits, loser.events
        assert hits[0]["winner_trace_id"] == winner.trace_id
        # both queries hashed to the same fleet digest
        won = [e for e in winner.events if e["name"] == "singleflight_won"]
        assert won[0]["digest"] == hits[0]["digest"]

    @pytest.mark.slow
    def test_two_real_processes_link_traces(self, tmp_path):
        """The real thing: two OS processes over one lake with obs on.
        Cross-process single-flight must link a loser's spool hit to a
        root trace id owned by the OTHER process, and the querylog must
        union per-process files to one row per execution."""
        from hyperspace_tpu.testing import fleet_harness

        out = fleet_harness.run_fleet(
            str(tmp_path / "fleet"),
            n_procs=2,
            iters=3,
            rows=12_000,
            conf={
                C.OBS_ENABLED: True,
                C.OBS_TRACE_RETAIN: 4096,
                # durable-plane linkage under test: force the claim/
                # spool election path, not routed owner handoffs
                C.FLEET_FAST_ENABLED: False,
            },
        )
        assert out["wrong_answers"] == 0
        assert out["cross_process_dedup"] > 0
        assert out["leaked_pin_files"] == 0
        obs_reports = out["worker_obs"]
        assert len(obs_reports) == 2
        roots_by_worker = [set(r["root_trace_ids"]) for r in obs_reports]
        assert roots_by_worker[0].isdisjoint(roots_by_worker[1])
        all_roots = roots_by_worker[0] | roots_by_worker[1]
        links = [
            (wi, link)
            for wi, r in enumerate(obs_reports)
            for link in r["spool_hit_links"]
            if link
        ]
        assert links, "no spool hit carried a winner trace id"
        for _wi, link in links:
            assert link in all_roots
        # later iterations legitimately hit a worker's OWN earlier
        # spooled result; the linkage contract needs at least one
        # CROSS-process link (loser -> the other process's root)
        assert any(
            link not in roots_by_worker[wi] for wi, link in links
        ), "no cross-process trace link observed"
        # querylog: per-process files union to one row per execution
        index_root = os.path.join(str(tmp_path / "fleet"), "indexes")
        records = querylog.read_records(
            os.path.join(index_root, C.HYPERSPACE_OBS_DIR)
        )
        assert records, "no querylog rows from the fleet"
        writers = {r["trace_id"] for r in records}
        # every recorded trace belongs to some worker's root set
        # (warmup serves are roots too; subset, not equality)
        assert {r["trace_id"] for r in records if r["trace_id"] in all_roots}
        for r in records:
            assert querylog.validate_record(r) is None, r
        assert len(writers) == len(set(writers))

    def test_bus_event_carries_action_trace_id(
        self, session_factory, tmp_path
    ):
        from hyperspace_tpu.serve import bus as fleet_bus
        from hyperspace_tpu.session import HyperspaceSession

        src = tmp_path / "src"
        src.mkdir()
        pq.write_table(
            pa.table({"k": pa.array(range(100), pa.int64())}),
            str(src / "p0.parquet"),
        )
        s = HyperspaceSession()
        s.conf.set(C.INDEX_SYSTEM_PATH, str(tmp_path / "indexes"))
        s.conf.set(C.INDEX_NUM_BUCKETS, 2)
        s.conf.set(C.FLEET_ENABLED, True)
        s.conf.set(C.OBS_ENABLED, True)
        s.enable_hyperspace()
        hs = Hyperspace(s)
        hs.create_index(
            s.read.parquet(str(src)), CoveringIndexConfig("bi1", ["k"], [])
        )
        roots = trace.finished("action.CreateAction")
        assert len(roots) == 1
        bus = fleet_bus.FleetBus(fleet_bus.bus_dir(s.conf), owner="probe")
        bus.prime = lambda: None  # see every event, incl. history
        bus._primed = True
        events = bus.poll_once()
        changed = [e for e in events if e.get("type") == "index_changed"]
        assert changed
        assert changed[-1]["trace_id"] == roots[0].trace_id
