"""Lifecycle action tests: delete/restore/vacuum/cancel/refresh/optimize.

Mirrors the reference's per-action suites (``actions/*ActionTest.scala``)
plus refresh E2E scenarios (append/delete matrices of
``RefreshIndexTest``/``HybridScanSuite``).
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


def state_of(session, name):
    return session.index_manager.get_index_log_entry(name).state


def append_file(sample_parquet, name="extra", clicks=(9001, 9002, 9003)):
    t = pa.table(
        {
            "date": ["2018-02-02"] * len(clicks),
            "rguid": [f"g{i}" for i in range(len(clicks))],
            "clicks": pa.array(list(clicks), pa.int64()),
            "query": ["appended"] * len(clicks),
            "imprs": pa.array(list(range(len(clicks))), pa.int64()),
        }
    )
    pq.write_table(t, os.path.join(sample_parquet, f"part-{name}.parquet"))


class TestDeleteRestoreVacuum:
    def test_delete_restore_roundtrip(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        hs.delete_index("idx")
        assert state_of(session, "idx") == States.DELETED
        # deleted index is not used
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        plan = df.filter(df["clicks"] > 1).select("clicks", "query").explain()
        assert "Hyperspace" not in plan
        hs.restore_index("idx")
        assert state_of(session, "idx") == States.ACTIVE
        session.index_manager.clear_cache()
        plan = df.filter(df["clicks"] > 1).select("clicks", "query").explain()
        assert "Hyperspace" in plan

    def test_delete_requires_active(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"]))
        hs.delete_index("idx")
        with pytest.raises(HyperspaceException, match="requires state ACTIVE"):
            hs.delete_index("idx")

    def test_vacuum_deleted_removes_everything(
        self, session, hs, sample_parquet, tmp_index_root
    ):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"]))
        hs.delete_index("idx")
        hs.vacuum_index("idx")
        assert state_of(session, "idx") == States.DOESNOTEXIST
        idx_dir = os.path.join(tmp_index_root, "idx")
        leftover = [
            d for d in os.listdir(idx_dir) if d != C.HYPERSPACE_LOG_DIR
        ]
        assert leftover == []
        # name reusable after vacuum
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"]))
        assert state_of(session, "idx") == States.ACTIVE

    def test_vacuum_outdated_keeps_only_live_versions(
        self, session, hs, sample_parquet, tmp_index_root
    ):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        append_file(sample_parquet)
        hs.refresh_index("idx", "full")  # new version dir v__=2
        hs.vacuum_index("idx")  # ACTIVE -> vacuum outdated
        assert state_of(session, "idx") == States.ACTIVE
        idx_dir = os.path.join(tmp_index_root, "idx")
        versions = [d for d in os.listdir(idx_dir) if d.startswith("v__=")]
        assert versions == ["v__=2"]
        # still serves correctly
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 9000).select("clicks", "query")
        assert "Hyperspace" in q(df2).explain()
        assert q(df2).count() == 3


class TestCancel:
    def test_cancel_rolls_back_transient_state(
        self, session, hs, sample_parquet, monkeypatch
    ):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))

        # Make refresh fail mid-op, leaving REFRESHING in the log
        from hyperspace_tpu.actions import refresh as refresh_mod

        def boom(self):
            raise RuntimeError("simulated op failure")

        append_file(sample_parquet)
        monkeypatch.setattr(refresh_mod.RefreshAction, "op", boom)
        with pytest.raises(RuntimeError):
            hs.refresh_index("idx", "full")
        log_mgr, _ = session.index_manager._managers("idx")
        assert log_mgr.get_latest_log().state == States.REFRESHING
        # all operations blocked until cancel
        monkeypatch.undo()
        with pytest.raises(HyperspaceException):
            hs.delete_index("idx")  # stable log says ACTIVE but ids advanced
        hs.cancel("idx")
        assert log_mgr.get_latest_log().state == States.ACTIVE
        hs.delete_index("idx")  # now works
        assert state_of(session, "idx") == States.DELETED

    def test_cancel_requires_transient(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"]))
        with pytest.raises(HyperspaceException, match="transient"):
            hs.cancel("idx")


class TestRefresh:
    def _mk(self, session, hs, sample_parquet, lineage=False):
        if lineage:
            session.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        return df

    def test_refresh_full_after_append(self, session, hs, sample_parquet):
        self._mk(session, hs, sample_parquet)
        append_file(sample_parquet)
        hs.refresh_index("idx", "full")
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 9000).select("clicks", "query")
        plan = q(df2).explain()
        assert "Hyperspace(Type: CI, Name: idx" in plan
        session.disable_hyperspace()
        base = q(df2).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df2).collect()).equals(sorted_table(base))
        assert q(df2).count() == 3

    def test_refresh_noop_when_unchanged(self, session, hs, sample_parquet):
        self._mk(session, hs, sample_parquet)
        log_mgr, _ = session.index_manager._managers("idx")
        before = log_mgr.get_latest_id()
        hs.refresh_index("idx", "full")  # NoChangesException swallowed
        assert log_mgr.get_latest_id() == before

    def test_refresh_incremental_append_only(self, session, hs, sample_parquet):
        self._mk(session, hs, sample_parquet)
        append_file(sample_parquet)
        hs.refresh_index("idx", "incremental")
        entry = session.index_manager.get_index_log_entry("idx")
        # merged content spans two version dirs
        versions = {f.split("v__=")[1].split("/")[0] for f in entry.content.files}
        assert versions == {"1", "2"}
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 500).select("clicks", "query")
        plan = q(df2).explain()
        assert "Hyperspace" in plan
        session.disable_hyperspace()
        base = q(df2).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df2).collect()).equals(sorted_table(base))

    def test_refresh_incremental_delete_requires_lineage(
        self, session, hs, sample_parquet
    ):
        self._mk(session, hs, sample_parquet, lineage=False)
        os.remove(os.path.join(sample_parquet, "part-0.parquet"))
        with pytest.raises(HyperspaceException, match="lineage"):
            hs.refresh_index("idx", "incremental")

    def test_refresh_incremental_with_deletes(self, session, hs, sample_parquet):
        self._mk(session, hs, sample_parquet, lineage=True)
        os.remove(os.path.join(sample_parquet, "part-0.parquet"))
        append_file(sample_parquet)
        hs.refresh_index("idx", "incremental")
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 0).select("clicks", "query")
        plan = q(df2).explain()
        assert "Hyperspace" in plan
        session.disable_hyperspace()
        base = q(df2).collect()
        session.enable_hyperspace()
        got = q(df2).collect()
        assert sorted_table(got).equals(sorted_table(base))
        assert got.num_rows == 203  # 300 - 100 deleted + 3 appended

    def test_refresh_quick_then_hybrid_serve(self, session, hs, sample_parquet):
        self._mk(session, hs, sample_parquet, lineage=True)
        append_file(sample_parquet)
        hs.refresh_index("idx", "quick")
        entry = session.index_manager.get_index_log_entry("idx")
        assert entry.relation.update is not None
        assert entry.relation.update.appended_files is not None
        # quick refresh + hybrid scan serves fresh data from old index files
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 500).select("clicks", "query")
        plan = q(df2).explain()
        assert "Hyperspace" in plan
        session.disable_hyperspace()
        base = q(df2).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df2).collect()).equals(sorted_table(base))


    def test_quick_then_incremental_materializes_pending_files(
        self, session, hs, sample_parquet
    ):
        """Files recorded by a quick refresh were never indexed; a later
        incremental refresh must still materialize them."""
        self._mk(session, hs, sample_parquet, lineage=True)
        append_file(sample_parquet)
        hs.refresh_index("idx", "quick")
        hs.refresh_index("idx", "incremental")  # must NOT be a no-op
        entry = session.index_manager.get_index_log_entry("idx")
        assert not entry.has_source_update
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 9000).select("clicks", "query")
        plan = q(df2).explain()
        assert "Hyperspace" in plan and "Union" not in plan
        assert q(df2).count() == 3  # appended rows served from index data

    def test_refresh_quick_serves_in_exact_mode(
        self, session, hs, sample_parquet
    ):
        """Quick refresh must keep the index usable WITHOUT hybrid scan:
        the rewrite compensates from the recorded Update delta."""
        self._mk(session, hs, sample_parquet, lineage=True)
        append_file(sample_parquet)
        hs.refresh_index("idx", "quick")
        session.enable_hyperspace()  # hybrid scan stays DISABLED
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 500).select("clicks", "query")
        plan = q(df2).explain()
        assert "Hyperspace" in plan and "Union" in plan
        session.disable_hyperspace()
        base = q(df2).collect()
        session.enable_hyperspace()
        got = q(df2).collect()
        assert sorted_table(got).equals(sorted_table(base))
        assert "appended" in got.column("query").to_pylist()


    def test_quick_refresh_delete_without_lineage_rejected_not_crashed(
        self, session, hs, sample_parquet
    ):
        """Exact-mode queries must reject (not crash on) a lineage-less
        quick-refreshed index that recorded deletes."""
        self._mk(session, hs, sample_parquet, lineage=False)
        os.remove(os.path.join(sample_parquet, "part-0.parquet"))
        hs.refresh_index("idx", "quick")
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 0).select("clicks", "query")
        plan = q(df2).explain()
        assert "Hyperspace" not in plan
        assert q(df2).count() == 200  # correct results from source scan

    def test_second_quick_refresh_after_delete(
        self, session, hs, sample_parquet
    ):
        self._mk(session, hs, sample_parquet, lineage=True)
        os.remove(os.path.join(sample_parquet, "part-0.parquet"))
        hs.refresh_index("idx", "quick")
        append_file(sample_parquet)
        hs.refresh_index("idx", "quick")  # must not KeyError
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 0).select("clicks", "query")
        session.disable_hyperspace()
        base = q(df2).collect()
        session.enable_hyperspace()
        got = q(df2).collect()
        assert sorted_table(got).equals(sorted_table(base))
        assert got.num_rows == 203


class TestOptimize:
    def test_optimize_compacts_buckets(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        append_file(sample_parquet, "e1")
        hs.refresh_index("idx", "incremental")
        append_file(sample_parquet, "e2", clicks=(9101, 9102))
        hs.refresh_index("idx", "incremental")
        entry = session.index_manager.get_index_log_entry("idx")
        files_before = len(entry.content.files)
        hs.optimize_index("idx", "full")
        entry2 = session.index_manager.get_index_log_entry("idx")
        assert len(entry2.content.files) < files_before
        # results still correct
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 500).select("clicks", "query")
        session.disable_hyperspace()
        base = q(df2).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df2).collect()).equals(sorted_table(base))

    def test_optimize_noop_single_files(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"]))
        log_mgr, _ = session.index_manager._managers("idx")
        before = log_mgr.get_latest_id()
        hs.optimize_index("idx", "full")  # every bucket has 1 file -> no-op
        assert log_mgr.get_latest_id() == before

    def test_optimize_invalid_mode(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"]))
        with pytest.raises(HyperspaceException, match="mode"):
            hs.optimize_index("idx", "bogus")
