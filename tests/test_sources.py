"""Delta Lake + Iceberg source provider tests.

Mirrors ``index/DeltaLakeIntegrationTest.scala`` (711 LoC incl. time
travel & closestIndex) and ``IcebergIntegrationTest.scala`` with
hand-built table layouts (both formats are open specs; no Spark needed).
"""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


# ---------------------------------------------------------------------------
# Delta table builder
# ---------------------------------------------------------------------------

DELTA_SCHEMA = json.dumps(
    {
        "type": "struct",
        "fields": [
            {"name": "k", "type": "long", "nullable": True, "metadata": {}},
            {"name": "v", "type": "double", "nullable": True, "metadata": {}},
            {"name": "s", "type": "string", "nullable": True, "metadata": {}},
        ],
    }
)


class DeltaBuilder:
    def __init__(self, path):
        self.path = str(path)
        self.version = -1
        os.makedirs(os.path.join(self.path, "_delta_log"), exist_ok=True)

    def _commit(self, actions):
        self.version += 1
        p = os.path.join(
            self.path, "_delta_log", f"{self.version:020d}.json"
        )
        with open(p, "w") as f:
            for a in actions:
                f.write(json.dumps(a) + "\n")

    def _write_file(self, name, k0):
        t = pa.table(
            {
                "k": pa.array(range(k0, k0 + 50), type=pa.int64()),
                "v": pa.array(np.linspace(0, 1, 50)),
                "s": [f"s{i%5}" for i in range(50)],
            }
        )
        fp = os.path.join(self.path, name)
        pq.write_table(t, fp)
        st = os.stat(fp)
        return {
            "path": name,
            "size": st.st_size,
            "modificationTime": int(st.st_mtime * 1000),
            "dataChange": True,
        }

    def init(self):
        add = self._write_file("part-0.parquet", 0)
        self._commit(
            [
                {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
                {
                    "metaData": {
                        "id": "test",
                        "schemaString": DELTA_SCHEMA,
                        "partitionColumns": [],
                        "format": {"provider": "parquet"},
                    }
                },
                {"add": add},
            ]
        )
        return self

    def append(self, name, k0):
        self._commit([{"add": self._write_file(name, k0)}])
        return self

    def remove(self, name):
        self._commit([{"remove": {"path": name, "dataChange": True}}])
        return self


class TestDeltaLog:
    def test_snapshot_versions(self, tmp_path):
        from hyperspace_tpu.sources import delta_log

        b = DeltaBuilder(tmp_path / "t").init().append("part-1.parquet", 100)
        snap = delta_log.read_snapshot(b.path)
        assert snap.version == 1 and len(snap.files) == 2
        snap0 = delta_log.read_snapshot(b.path, 0)
        assert snap0.version == 0 and len(snap0.files) == 1
        b.remove("part-0.parquet")
        snap2 = delta_log.read_snapshot(b.path)
        assert len(snap2.files) == 1
        assert [n for n, _ in snap.schema_fields] == ["k", "v", "s"]

    def test_checkpoint_replay(self, tmp_path):
        from hyperspace_tpu.sources import delta_log

        b = DeltaBuilder(tmp_path / "t").init().append("part-1.parquet", 100)
        # write a checkpoint at version 1 summarizing state
        snap = delta_log.read_snapshot(b.path)
        rows = [
            {
                "metaData": {"schemaString": DELTA_SCHEMA, "partitionColumns": []},
                "add": None,
            }
        ]
        for p, (size, mtime) in snap.files.items():
            rows.append(
                {
                    "metaData": None,
                    "add": {
                        "path": os.path.relpath(p, b.path),
                        "size": size,
                        "modificationTime": mtime,
                    },
                }
            )
        ckpt = pa.Table.from_pylist(rows)
        pq.write_table(
            ckpt, os.path.join(b.path, "_delta_log", f"{1:020d}.checkpoint.parquet")
        )
        # drop the raw jsons <= 1 to prove the checkpoint is used
        os.remove(os.path.join(b.path, "_delta_log", f"{0:020d}.json"))
        os.remove(os.path.join(b.path, "_delta_log", f"{1:020d}.json"))
        b.append("part-2.parquet", 200)
        snap2 = delta_log.read_snapshot(b.path)
        assert snap2.version == 2 and len(snap2.files) == 3

    def test_read_delta_dataframe(self, session, tmp_path):
        b = DeltaBuilder(tmp_path / "t").init().append("part-1.parquet", 100)
        df = session.read.delta(b.path)
        assert df.count() == 100
        df0 = session.read.delta(b.path, version_as_of=0)
        assert df0.count() == 50


class TestDeltaIndexing:
    def test_create_and_serve(self, session, hs, tmp_path):
        b = DeltaBuilder(tmp_path / "t").init().append("part-1.parquet", 100)
        df = session.read.delta(b.path)
        hs.create_index(df, CoveringIndexConfig("didx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = lambda d: d.filter(d["k"] >= 100).select("k", "v")
        plan = q(df).explain()
        assert "Hyperspace(Type: CI, Name: didx" in plan
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df).collect()).equals(sorted_table(base))
        # delta version history recorded on the index
        entry = session.index_manager.get_index_log_entry("didx")
        hist = entry.derived_dataset.properties[C.DELTA_VERSION_HISTORY_PROPERTY]
        assert hist == "2:1"  # log version 2 at delta version 1

    def test_new_commit_invalidates_then_refresh(self, session, hs, tmp_path):
        b = DeltaBuilder(tmp_path / "t").init()
        df = session.read.delta(b.path)
        hs.create_index(df, CoveringIndexConfig("didx", ["k"], ["v"]))
        b.append("part-1.parquet", 100)
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.delta(b.path)
        assert "Hyperspace" not in df2.filter(df2["k"] > 0).select("k", "v").explain()
        hs.refresh_index("didx", "incremental")
        session.index_manager.clear_cache()
        df3 = session.read.delta(b.path)
        q = lambda d: d.filter(d["k"] >= 100).select("k", "v")
        assert "Hyperspace" in q(df3).explain()
        session.disable_hyperspace()
        base = q(df3).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df3).collect()).equals(sorted_table(base))
        entry = session.index_manager.get_index_log_entry("didx")
        hist = entry.derived_dataset.properties[C.DELTA_VERSION_HISTORY_PROPERTY]
        assert hist == "2:0,4:1"

    def test_closest_index_time_travel(self, session, hs, tmp_path):
        b = DeltaBuilder(tmp_path / "t").init()
        df = session.read.delta(b.path)
        hs.create_index(df, CoveringIndexConfig("didx", ["k"], ["v"]))
        b.append("part-1.parquet", 100)
        hs.refresh_index("didx", "full")
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        # query pinned at delta version 0 -> the ORIGINAL index version
        # (log 2) must serve it, not the refreshed one (log 4)
        df0 = session.read.delta(b.path, version_as_of=0)
        q = lambda d: d.filter(d["k"] >= 0).select("k", "v")
        plan = q(df0).explain()
        assert "Name: didx, LogVersion: 2" in plan, plan
        session.disable_hyperspace()
        base = q(df0).collect()
        session.enable_hyperspace()
        got = q(df0).collect()
        assert sorted_table(got).equals(sorted_table(base))
        assert got.num_rows == 50


# ---------------------------------------------------------------------------
# Iceberg table builder (metadata JSON + avro manifests via utils/avro)
# ---------------------------------------------------------------------------

MANIFEST_ENTRY_SCHEMA = {
    "type": "record",
    "name": "manifest_entry",
    "fields": [
        {"name": "status", "type": "int"},
        {
            "name": "data_file",
            "type": {
                "type": "record",
                "name": "r2",
                "fields": [
                    {"name": "file_path", "type": "string"},
                    {"name": "file_size_in_bytes", "type": "long"},
                ],
            },
        },
    ],
}

MANIFEST_FILE_SCHEMA = {
    "type": "record",
    "name": "manifest_file",
    "fields": [{"name": "manifest_path", "type": "string"}],
}


class IcebergBuilder:
    def __init__(self, path):
        self.path = str(path)
        self.snapshots = []
        self.files = []
        os.makedirs(os.path.join(self.path, "metadata"), exist_ok=True)
        os.makedirs(os.path.join(self.path, "data"), exist_ok=True)

    def add_file(self, name, k0):
        from hyperspace_tpu.utils.avro import write_avro

        t = pa.table(
            {
                "k": pa.array(range(k0, k0 + 40), type=pa.int64()),
                "v": pa.array(np.linspace(0, 1, 40)),
            }
        )
        fp = os.path.join(self.path, "data", name)
        pq.write_table(t, fp)
        self.files.append((fp, os.stat(fp).st_size))
        return self

    def commit(self):
        from hyperspace_tpu.utils.avro import write_avro

        sid = len(self.snapshots) + 1
        manifest = os.path.join(self.path, "metadata", f"manifest-{sid}.avro")
        write_avro(
            manifest,
            MANIFEST_ENTRY_SCHEMA,
            [
                {
                    "status": 1,
                    "data_file": {"file_path": p, "file_size_in_bytes": size},
                }
                for p, size in self.files
            ],
        )
        mlist = os.path.join(self.path, "metadata", f"snap-{sid}.avro")
        write_avro(mlist, MANIFEST_FILE_SCHEMA, [{"manifest_path": manifest}])
        self.snapshots.append(
            {
                "snapshot-id": sid,
                "timestamp-ms": 1700000000000 + sid,
                "manifest-list": mlist,
            }
        )
        doc = {
            "format-version": 2,
            "location": self.path,
            "current-snapshot-id": sid,
            "snapshots": self.snapshots,
            "schema": {
                "type": "struct",
                "schema-id": 0,
                "fields": [
                    {"id": 1, "name": "k", "type": "long", "required": False},
                    {"id": 2, "name": "v", "type": "double", "required": False},
                ],
            },
        }
        mf = os.path.join(self.path, "metadata", f"v{sid}.metadata.json")
        with open(mf, "w") as f:
            json.dump(doc, f)
        with open(
            os.path.join(self.path, "metadata", "version-hint.text"), "w"
        ) as f:
            f.write(str(sid))
        return self


class TestAvro:
    def test_roundtrip(self, tmp_path):
        from hyperspace_tpu.utils.avro import read_avro, write_avro

        schema = {
            "type": "record",
            "name": "r",
            "fields": [
                {"name": "a", "type": "long"},
                {"name": "b", "type": ["null", "string"]},
                {"name": "c", "type": {"type": "array", "items": "int"}},
                {"name": "d", "type": {"type": "map", "values": "double"}},
                {"name": "e", "type": "boolean"},
            ],
        }
        recs = [
            {"a": -1, "b": "x", "c": [1, 2, 3], "d": {"p": 0.5}, "e": True},
            {"a": 2**40, "b": None, "c": [], "d": {}, "e": False},
        ]
        p = str(tmp_path / "t.avro")
        write_avro(p, schema, recs)
        assert read_avro(p) == recs


class TestIceberg:
    def test_read_and_snapshot_pinning(self, session, tmp_path):
        b = IcebergBuilder(tmp_path / "it").add_file("f0.parquet", 0).commit()
        b.add_file("f1.parquet", 100).commit()
        df = session.read.iceberg(b.path)
        assert df.count() == 80
        df1 = session.read.iceberg(b.path, snapshot_id=1)
        assert df1.count() == 40

    def test_create_and_serve(self, session, hs, tmp_path):
        b = IcebergBuilder(tmp_path / "it").add_file("f0.parquet", 0).commit()
        df = session.read.iceberg(b.path)
        hs.create_index(df, CoveringIndexConfig("iidx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = lambda d: d.filter(d["k"] >= 10).select("k", "v")
        plan = q(df).explain()
        assert "Hyperspace(Type: CI, Name: iidx" in plan
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df).collect()).equals(sorted_table(base))

    def test_new_snapshot_invalidates(self, session, hs, tmp_path):
        b = IcebergBuilder(tmp_path / "it").add_file("f0.parquet", 0).commit()
        df = session.read.iceberg(b.path)
        hs.create_index(df, CoveringIndexConfig("iidx", ["k"], ["v"]))
        b.add_file("f1.parquet", 100).commit()
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.iceberg(b.path)
        assert "Hyperspace" not in df2.filter(df2["k"] > 0).select("k", "v").explain()
        hs.refresh_index("iidx", "incremental")
        session.index_manager.clear_cache()
        df3 = session.read.iceberg(b.path)
        assert "Hyperspace" in df3.filter(df3["k"] > 0).select("k", "v").explain()


class TestDeltaCheckpointFormats:
    def _checkpoint_rows(self, b):
        from hyperspace_tpu.sources import delta_log

        snap = delta_log.read_snapshot(b.path)
        rows = [
            {
                "metaData": {"schemaString": DELTA_SCHEMA, "partitionColumns": []},
                "add": None,
            }
        ]
        for p, (size, mtime) in snap.files.items():
            rows.append(
                {
                    "metaData": None,
                    "add": {
                        "path": os.path.relpath(p, b.path),
                        "size": size,
                        "modificationTime": mtime,
                    },
                }
            )
        return rows, snap.version

    def test_multipart_checkpoint(self, tmp_path):
        from hyperspace_tpu.sources import delta_log

        b = (
            DeltaBuilder(tmp_path / "t")
            .init()
            .append("part-1.parquet", 100)
            .append("part-2.parquet", 200)
        )
        rows, v = self._checkpoint_rows(b)
        log_dir = os.path.join(b.path, "_delta_log")
        # split the checkpoint into 2 parts: NNN.checkpoint.MMM.PPP.parquet
        half = len(rows) // 2
        for part, chunk in ((1, rows[:half]), (2, rows[half:])):
            pq.write_table(
                pa.Table.from_pylist(chunk),
                os.path.join(
                    log_dir, f"{v:020d}.checkpoint.{part:010d}.{2:010d}.parquet"
                ),
            )
        with open(os.path.join(log_dir, "_last_checkpoint"), "w") as f:
            json.dump({"version": v, "size": len(rows), "parts": 2}, f)
        for j in range(v + 1):
            os.remove(os.path.join(log_dir, f"{j:020d}.json"))
        b.append("part-3.parquet", 300)
        snap = delta_log.read_snapshot(b.path)
        assert snap.version == v + 1 and len(snap.files) == 4

    def test_incomplete_multipart_checkpoint_ignored(self, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceException
        from hyperspace_tpu.sources import delta_log

        b = DeltaBuilder(tmp_path / "t").init().append("part-1.parquet", 100)
        rows, v = self._checkpoint_rows(b)
        log_dir = os.path.join(b.path, "_delta_log")
        # only part 1 of 2 present -> unusable; must not be picked up
        pq.write_table(
            pa.Table.from_pylist(rows),
            os.path.join(
                log_dir, f"{v:020d}.checkpoint.{1:010d}.{2:010d}.parquet"
            ),
        )
        snap = delta_log.read_snapshot(b.path)  # replays JSON instead
        assert len(snap.files) == 2
        os.remove(os.path.join(log_dir, f"{0:020d}.json"))
        with pytest.raises(HyperspaceException, match="missing commits"):
            delta_log.read_snapshot(b.path)

    def test_v2_checkpoint_rejected_clearly(self, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceException
        from hyperspace_tpu.sources import delta_log

        b = DeltaBuilder(tmp_path / "t").init().append("part-1.parquet", 100)
        rows, v = self._checkpoint_rows(b)
        log_dir = os.path.join(b.path, "_delta_log")
        pq.write_table(
            pa.Table.from_pylist(rows),
            os.path.join(
                log_dir,
                f"{v:020d}.checkpoint.80a083e8-7026-4e79-81be-64bd76c43a11.parquet",
            ),
        )
        for j in range(v + 1):
            os.remove(os.path.join(log_dir, f"{j:020d}.json"))
        with pytest.raises(HyperspaceException, match="uuid-named"):
            delta_log.read_snapshot(b.path)


class TestIcebergDeleteManifests:
    def test_delete_manifest_rejected(self, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceException
        from hyperspace_tpu.sources import iceberg_meta
        from hyperspace_tpu.utils.avro import write_avro

        b = IcebergBuilder(tmp_path / "t").add_file("f0.parquet", 0).commit()
        # append a delete manifest (content=1) to the current manifest list
        sid = len(b.snapshots)
        mlist = os.path.join(b.path, "metadata", f"snap-{sid}.avro")
        schema = {
            "type": "record",
            "name": "manifest_file",
            "fields": [
                {"name": "manifest_path", "type": "string"},
                {"name": "content", "type": "int"},
            ],
        }
        manifest = os.path.join(b.path, "metadata", f"manifest-{sid}.avro")
        write_avro(
            mlist,
            schema,
            [
                {"manifest_path": manifest, "content": 0},
                {"manifest_path": manifest, "content": 1},
            ],
        )
        with pytest.raises(HyperspaceException, match="live delete files"):
            iceberg_meta.read_snapshot(b.path)

    def test_delete_data_file_rejected(self, tmp_path):
        from hyperspace_tpu.exceptions import HyperspaceException
        from hyperspace_tpu.sources import iceberg_meta
        from hyperspace_tpu.utils.avro import write_avro

        b = IcebergBuilder(tmp_path / "t").add_file("f0.parquet", 0).commit()
        sid = len(b.snapshots)
        manifest = os.path.join(b.path, "metadata", f"manifest-{sid}.avro")
        schema = {
            "type": "record",
            "name": "manifest_entry",
            "fields": [
                {"name": "status", "type": "int"},
                {
                    "name": "data_file",
                    "type": {
                        "type": "record",
                        "name": "r2",
                        "fields": [
                            {"name": "content", "type": "int"},
                            {"name": "file_path", "type": "string"},
                            {"name": "file_size_in_bytes", "type": "long"},
                        ],
                    },
                },
            ],
        }
        write_avro(
            manifest,
            schema,
            [
                {
                    "status": 1,
                    "data_file": {
                        "content": 2,
                        "file_path": b.files[0][0],
                        "file_size_in_bytes": b.files[0][1],
                    },
                }
            ],
        )
        with pytest.raises(HyperspaceException, match="row-level delete"):
            iceberg_meta.read_snapshot(b.path)
