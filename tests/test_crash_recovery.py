"""Crash-point injection + stranded-action recovery (docs/recovery.md).

The tested contract (ISSUE 10): for every action × crash point cell of
the matrix, a writer killed at that point leaves the log recoverable —
after recovery the log tip is STABLE, a serve answers identically to
the unindexed truth, orphan GC returns the index directory's data file
set to exactly what a crash-free history would hold, and a retried
action completes. hslint HS703 requires every ``CRASH_POINTS`` entry to
appear in this file.

Tier-1 runs the in-process ``SimulatedCrash`` matrix; the ``os._exit``
subprocess variants (true torn state: no finally blocks, no heartbeat
shutdown) are slow-marked.
"""

import os
import subprocess
import sys
import threading
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import (
    ConcurrentWriteException,
    HyperspaceException,
    LogCorruptedError,
)
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.metadata import recovery
from hyperspace_tpu.testing import faults
from hyperspace_tpu.testing.faults import SimulatedCrash
from hyperspace_tpu.utils import files as file_utils
from hyperspace_tpu.utils.paths import is_data_path

LEASE_MS = 40


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def wait_lease():
    time.sleep(LEASE_MS * 2.5 / 1000.0)


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


def append_file(src, name="extra", clicks=(9001, 9002, 9003)):
    t = pa.table(
        {
            "date": ["2018-02-02"] * len(clicks),
            "rguid": [f"g{i}" for i in range(len(clicks))],
            "clicks": pa.array(list(clicks), pa.int64()),
            "query": ["appended"] * len(clicks),
            "imprs": pa.array(list(range(len(clicks))), pa.int64()),
        }
    )
    pq.write_table(t, os.path.join(src, f"part-{name}.parquet"))


def data_files(index_path):
    """Data file set under the index's version dirs (quarantine and log
    excluded) — the clean-build equivalence check."""
    out = set()
    if not os.path.isdir(index_path):
        return out
    for name in os.listdir(index_path):
        if name in (C.HYPERSPACE_LOG_DIR, C.HYPERSPACE_QUARANTINE_DIR):
            continue
        root = os.path.join(index_path, name)
        if not os.path.isdir(root):
            continue
        for p, _s, _m in file_utils.list_leaf_files(root):
            if is_data_path(p):
                out.add(p.replace("\\", "/"))
    return out


@pytest.fixture
def env(session_factory, sample_parquet):
    s = session_factory(1)
    s.conf.set(C.RECOVERY_LEASE_MS, LEASE_MS)
    s.conf.set(C.RECOVERY_ORPHAN_GRACE_MS, 0)
    s.conf.set(C.INDEX_LINEAGE_ENABLED, True)
    return s, Hyperspace(s), sample_parquet


def assert_serve_matches_source(session, src):
    df = session.read.parquet(src)
    q = df.filter(df["clicks"] >= 500).select("clicks", "query")
    session.index_manager.clear_cache()
    session.disable_hyperspace()
    base = q.collect()
    session.enable_hyperspace()
    got = q.collect()
    assert sorted_table(got).equals(sorted_table(base))
    session.disable_hyperspace()


# ---------------------------------------------------------------------------
# Crash registry
# ---------------------------------------------------------------------------


class TestCrashRegistry:
    def test_spec_parsing(self):
        assert faults.parse_crash_spec("off") is None
        assert faults.parse_crash_spec("") is None
        assert faults.parse_crash_spec("raise") == (False, 1, None)
        assert faults.parse_crash_spec("exit") == (True, 1, None)
        assert faults.parse_crash_spec("raise;at=3") == (False, 3, None)
        assert faults.parse_crash_spec("exit;match=v__=2") == (
            True,
            1,
            "v__=2",
        )
        for bad in ("boom", "raise;at=0", "raise;x=1"):
            with pytest.raises(ValueError):
                faults.parse_crash_spec(bad)
        with pytest.raises(ValueError):
            faults.set_crash("not_a_point", "raise")

    def test_raise_is_one_shot(self):
        faults.set_crash("after_begin_log", "raise")
        with pytest.raises(SimulatedCrash) as ei:
            faults.crash("after_begin_log", "CreateAction")
        assert ei.value.point == "after_begin_log"
        # disarmed itself: recovery running the same seam must not die
        faults.crash("after_begin_log", "CreateAction")
        assert faults.stats() == {"crash.after_begin_log": 1}

    def test_at_and_match(self):
        faults.set_crash("mid_data_write", "raise;at=2;match=special")
        faults.crash("mid_data_write", "/other/f1")  # no match
        faults.crash("mid_data_write", "/special/f1")  # call 1 of 2
        with pytest.raises(SimulatedCrash):
            faults.crash("mid_data_write", "/special/f2")

    def test_simulated_crash_is_not_exception(self):
        # an `except Exception` cleanup handler must never swallow a
        # simulated process death
        assert issubclass(SimulatedCrash, BaseException)
        assert not issubclass(SimulatedCrash, Exception)

    def test_configure_routes_crash_keys(self):
        from hyperspace_tpu.config import Config

        conf = Config()
        conf.set(C.CRASH_KEY_PREFIX + "after_end_log", "raise")
        conf.set(C.FAULTS_KEY_PREFIX + "log_read", "transient")
        assert faults.configure(conf) == 2
        with pytest.raises(SimulatedCrash):
            faults.crash("after_end_log")
        with pytest.raises(faults.InjectedFault):
            faults.check("log_read", "p")


# ---------------------------------------------------------------------------
# Recovery unit behavior: leases, rollback, healing, GC
# ---------------------------------------------------------------------------


class TestRecoveryUnit:
    def _mk_index(self, env):
        s, hs, src = env
        df = s.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        log_mgr, _ = s.index_manager._managers("idx")
        return s, hs, src, log_mgr

    def test_lease_stamped_and_heartbeat_renews(self, env, monkeypatch):
        s, hs, src, log_mgr = self._mk_index(env)
        append_file(src)
        from hyperspace_tpu.actions import refresh as refresh_mod

        seen = {}
        orig_op = refresh_mod.RefreshAction.op

        def slow_op(self):
            first = log_mgr.get_log(self.base_id + 1)
            # op outlives several heartbeat intervals; the lease must
            # have been re-stamped with a later expiry by the end
            time.sleep(LEASE_MS * 2.0 / 1000.0)
            seen["first"] = recovery.lease_expires_at(first, 0)
            seen["later"] = recovery.lease_expires_at(
                log_mgr.get_log(self.base_id + 1), 0
            )
            seen["owner"] = first.properties.get(recovery.LEASE_OWNER_PROP)
            return orig_op(self)

        monkeypatch.setattr(refresh_mod.RefreshAction, "op", slow_op)
        hs.refresh_index("idx", "full")
        assert seen["owner"]
        assert seen["later"] > seen["first"]
        # committed entries carry no lease
        assert (
            recovery.LEASE_OWNER_PROP
            not in log_mgr.get_latest_log().properties
        )

    def test_live_lease_blocks_auto_recovery(self, env):
        s, hs, src, log_mgr = self._mk_index(env)
        stable = log_mgr.get_latest_stable_log()
        stranded = stable.with_state(States.REFRESHING)
        recovery.stamp_lease(stranded, "w1", 60_000)
        assert log_mgr.write_log(log_mgr.get_latest_id() + 1, stranded)
        rep = recovery.ensure_recovered(log_mgr, lease_ms=60_000)
        assert rep["live_writer"] and not rep["rolled_back"]
        # once expired, the same entry rolls back
        rep = recovery.ensure_recovered(
            log_mgr, lease_ms=60_000, now=recovery.now_ms() + 120_000
        )
        assert rep["rolled_back"]
        assert log_mgr.get_latest_log().state == States.ACTIVE

    def test_rollback_occ_two_recoverers_single_roll(self, env):
        s, hs, src, log_mgr = self._mk_index(env)
        stable = log_mgr.get_latest_stable_log()
        tip = log_mgr.get_latest_id() + 1
        stranded = stable.with_state(States.OPTIMIZING)
        recovery.stamp_lease(stranded, "dead", 1)
        assert log_mgr.write_log(tip, stranded)
        wait_lease()
        # recoverer B wins the rollback id first
        other = stable.copy()
        assert log_mgr.write_log(tip + 1, other)
        # recoverer A loses the OCC race gracefully: no double-roll,
        # and the status says the survivor is B's write, not A's
        rolled, we_wrote = recovery.rollback(log_mgr, tip)
        assert rolled is not None and rolled.state == States.ACTIVE
        assert not we_wrote
        assert log_mgr.get_latest_id() == tip + 1

    def test_stale_pointer_healed(self, env):
        s, hs, src, log_mgr = self._mk_index(env)
        append_file(src)
        hs.refresh_index("idx", "full")
        latest = log_mgr.get_latest_id()
        # simulate a crash between end-log and publish: pointer rewound
        log_mgr.create_latest_stable_log(latest - 2)
        assert log_mgr.get_latest_stable_pointer_id() == latest - 2
        rep = recovery.ensure_recovered(log_mgr, LEASE_MS)
        assert rep["healed_pointer"]
        assert log_mgr.get_latest_stable_pointer_id() == latest

    def test_gc_skips_live_writer_version_dir(self, env):
        """GC must never quarantine a LIVE writer's half-written files:
        they are referenced by no entry yet, and only the lease can tell
        in-progress work from a dead writer's leavings."""
        s, hs, src, log_mgr = self._mk_index(env)
        index_path = log_mgr.index_path
        # simulate a writer mid-op: transient tip with a live lease and
        # an unreferenced in-progress version dir
        stable = log_mgr.get_latest_stable_log()
        busy = stable.with_state(States.REFRESHING)
        recovery.stamp_lease(busy, "live", 60_000)
        assert log_mgr.write_log(log_mgr.get_latest_id() + 1, busy)
        wip_dir = os.path.join(index_path, "v__=2")
        os.makedirs(wip_dir)
        wip = os.path.join(wip_dir, "part-wip.parquet")
        with open(wip, "w") as f:
            f.write("x")
        rep = recovery.gc_orphans(index_path, grace_ms=0, lease_ms=60_000)
        assert rep["skipped_live_writer"]
        assert rep["quarantined_files"] == 0 and rep["quarantined_dirs"] == 0
        assert os.path.isfile(wip)
        # once the lease expires the same files are fair game
        rep = recovery.gc_orphans(
            index_path, grace_ms=0, lease_ms=60_000,
            now=recovery.now_ms() + 120_000,
        )
        assert not rep["skipped_live_writer"]
        assert rep["quarantined_dirs"] == 1
        assert not os.path.exists(wip)

    def test_gc_respects_pins_and_grace(self, env):
        s, hs, src, log_mgr = self._mk_index(env)
        index_path = log_mgr.index_path
        # strand an orphan: a version dir no stable entry references
        orphan_dir = os.path.join(index_path, "v__=9")
        os.makedirs(orphan_dir)
        orphan = os.path.join(orphan_dir, "part-orphan.parquet")
        with open(orphan, "w") as f:
            f.write("x")
        assert recovery.find_orphans(index_path) == [orphan]
        # a pinned snapshot naming the file blocks quarantine
        entry = log_mgr.get_latest_stable_log().copy()
        from hyperspace_tpu.metadata.entry import Content

        entry.content = Content.from_leaf_files([(orphan, 1, 1)])
        token = recovery.register_pins([entry])
        rep = recovery.gc_orphans(index_path, grace_ms=0)
        assert rep["kept_pinned"] == 1 and os.path.isfile(orphan)
        recovery.release_pins(token)
        # unpinned: quarantined but NOT purged inside the grace window
        rep = recovery.gc_orphans(index_path, grace_ms=10 * 60_000)
        assert rep["quarantined_dirs"] == 1
        assert not os.path.exists(orphan)
        qroot = os.path.join(index_path, C.HYPERSPACE_QUARANTINE_DIR)
        assert os.path.isdir(qroot) and os.listdir(qroot)
        assert rep["purged_stamps"] == 0
        # grace elapsed: purged
        rep = recovery.gc_orphans(
            index_path, grace_ms=10 * 60_000,
            now=recovery.now_ms() + 11 * 60_000,
        )
        assert rep["purged_stamps"] == 1
        assert not os.path.exists(qroot)

    def test_torn_entry_is_stranded_not_fatal(self, env):
        s, hs, src, log_mgr = self._mk_index(env)
        tip = log_mgr.get_latest_id() + 1
        with open(log_mgr._path_for(tip), "w") as f:
            f.write('{"state": "REFRESH')  # torn mid-write
        with pytest.raises(LogCorruptedError):
            log_mgr.get_log(tip)
        # reads route around it...
        assert log_mgr.get_latest_stable_log().state == States.ACTIVE
        # ...and recovery rolls it back like any dead writer
        rep = recovery.ensure_recovered(log_mgr, LEASE_MS)
        assert rep["rolled_back"]
        assert log_mgr.get_latest_log().state == States.ACTIVE

    def test_torn_first_create_clears_to_doesnotexist(self, env, tmp_path):
        s, hs, src = env
        from hyperspace_tpu.metadata.log_manager import IndexLogManager

        log_mgr = IndexLogManager(str(tmp_path / "fresh_idx"))
        os.makedirs(log_mgr.log_dir)
        with open(log_mgr._path_for(1), "w") as f:
            f.write("{notjson")
        rep = recovery.ensure_recovered(log_mgr, LEASE_MS)
        assert rep["rolled_back"]
        assert log_mgr.get_latest_id() is None  # name reusable

    def test_recover_all_invalidates_entry_cache(self, env):
        """A user-invoked recover_all() that rolls a log back must not
        leave the TTL entry cache serving the pre-rollback snapshot."""
        s, hs, src, log_mgr = self._mk_index(env)
        s.index_manager.get_indexes()  # populate the TTL cache
        stable = log_mgr.get_latest_stable_log()
        stranded = stable.with_state(States.REFRESHING)
        recovery.stamp_lease(stranded, "dead", 1)
        assert log_mgr.write_log(log_mgr.get_latest_id() + 1, stranded)
        wait_lease()
        reports = s.index_manager.recover_all()
        assert any(r["rolled_back"] for r in reports)
        fresh = s.index_manager.get_indexes([States.ACTIVE])
        assert [e.id for e in fresh] == [log_mgr.get_latest_id()]

    def test_session_attach_sweeps_stranded_entries(
        self, env, session_factory
    ):
        s, hs, src, log_mgr = self._mk_index(env)
        stable = log_mgr.get_latest_stable_log()
        stranded = stable.with_state(States.REFRESHING)
        recovery.stamp_lease(stranded, "dead", 1)
        assert log_mgr.write_log(log_mgr.get_latest_id() + 1, stranded)
        wait_lease()
        # a NEW session over the same system path repairs at attach
        s2 = session_factory(1)
        s2.conf.set(C.RECOVERY_LEASE_MS, LEASE_MS)
        assert s2.index_manager is not None  # triggers attach sweep
        assert log_mgr.get_latest_log().state == States.ACTIVE


# ---------------------------------------------------------------------------
# The crash matrix: every action x every applicable crash point
# ---------------------------------------------------------------------------

# action -> (applicable crash points, state after rollback of a
# pre-commit crash, state after an after_end_log crash + pointer heal)
MATRIX = {
    "create": (
        [
            "after_begin_log",
            "mid_data_write",
            "after_data_write",
            "after_end_log",
        ],
        States.DOESNOTEXIST,
        States.ACTIVE,
    ),
    "refresh_full": (
        [
            "after_begin_log",
            "mid_data_write",
            "after_data_write",
            "after_end_log",
        ],
        States.ACTIVE,
        States.ACTIVE,
    ),
    "refresh_incremental": (
        [
            "after_begin_log",
            "mid_data_write",
            "after_data_write",
            "after_end_log",
        ],
        States.ACTIVE,
        States.ACTIVE,
    ),
    "refresh_quick": (
        ["after_begin_log", "after_data_write", "after_end_log"],
        States.ACTIVE,
        States.ACTIVE,
    ),
    "optimize": (
        [
            "after_begin_log",
            "mid_data_write",
            "after_data_write",
            "after_end_log",
        ],
        States.ACTIVE,
        States.ACTIVE,
    ),
    "delete": (
        ["after_begin_log", "after_data_write", "after_end_log"],
        States.ACTIVE,
        States.DELETED,
    ),
    "restore": (
        ["after_begin_log", "after_data_write", "after_end_log"],
        States.DELETED,
        States.ACTIVE,
    ),
    "vacuum_deleted": (
        [
            "after_begin_log",
            "mid_vacuum_delete",
            "after_data_write",
            "after_end_log",
        ],
        States.DELETED,
        States.DOESNOTEXIST,
    ),
    "vacuum_outdated": (
        [
            "after_begin_log",
            "mid_vacuum_delete",
            "after_data_write",
            "after_end_log",
        ],
        States.ACTIVE,
        States.ACTIVE,
    ),
}

CELLS = [
    (action, point)
    for action, (points, _r, _f) in MATRIX.items()
    for point in points
]


class TestCrashMatrix:
    def _setup(self, env, action):
        """Build the action's precondition state; return its trigger."""
        s, hs, src = env
        df = s.read.parquet(src)
        cfg = CoveringIndexConfig("idx", ["clicks"], ["query"])
        if action != "create":
            hs.create_index(df, cfg)
        if action.startswith("refresh"):
            append_file(src)
        elif action == "optimize":
            append_file(src, "e1")
            hs.refresh_index("idx", "incremental")
            append_file(src, "e2", clicks=(9101, 9102))
            hs.refresh_index("idx", "incremental")
        elif action in ("delete", "vacuum_outdated"):
            if action == "vacuum_outdated":
                append_file(src)
                hs.refresh_index("idx", "full")  # old version to sweep
        elif action in ("restore", "vacuum_deleted"):
            hs.delete_index("idx")

        def trigger():
            {
                "create": lambda: hs.create_index(
                    s.read.parquet(src), cfg
                ),
                "refresh_full": lambda: hs.refresh_index("idx", "full"),
                "refresh_incremental": lambda: hs.refresh_index(
                    "idx", "incremental"
                ),
                "refresh_quick": lambda: hs.refresh_index("idx", "quick"),
                "optimize": lambda: hs.optimize_index("idx", "full"),
                "delete": lambda: hs.delete_index("idx"),
                "restore": lambda: hs.restore_index("idx"),
                "vacuum_deleted": lambda: hs.vacuum_index("idx"),
                "vacuum_outdated": lambda: hs.vacuum_index("idx"),
            }[action]()

        return trigger

    @pytest.mark.parametrize(("action", "point"), CELLS)
    def test_crash_then_recover(self, env, action, point):
        s, hs, src = env
        trigger = self._setup(env, action)
        points, rolled_state, committed_state = MATRIX[action]
        log_mgr, _ = s.index_manager._managers("idx")
        index_path = log_mgr.index_path
        files_before = data_files(index_path)
        faults.set_crash(point, "raise")
        with pytest.raises(SimulatedCrash):
            trigger()
        assert faults.stats().get("crash." + point, 0) == 1
        committed = point == "after_end_log"
        if not committed:
            # the writer died mid-protocol: transient tip on disk
            assert log_mgr.get_latest_log().state not in States.STABLE_STATES
        wait_lease()
        rep = hs.recover("idx")
        tip = log_mgr.get_latest_log()
        if committed:
            assert rep["healed_pointer"] and not rep["rolled_back"]
            assert tip.state == committed_state
            assert log_mgr.get_latest_stable_pointer_id() == tip.id
        else:
            assert rep["rolled_back"]
            assert tip.state == rolled_state
            # crash-free file-set equivalence: rollback + GC returns the
            # data file set to exactly the pre-action state (vacuum may
            # already have deleted files — a subset — before dying)
            after = data_files(index_path)
            if action.startswith("vacuum"):
                assert after <= files_before
            else:
                assert after == files_before
        # zero orphans, and a second GC pass is a no-op
        assert recovery.find_orphans(index_path) == []
        gc2 = recovery.gc_orphans(index_path, grace_ms=0)
        assert gc2["quarantined_files"] == 0 and gc2["quarantined_dirs"] == 0
        # serve truth is untouched either way
        assert_serve_matches_source(s, src)
        # the retried action completes (already-committed ops surface as
        # no-op / illegal-state; both fine)
        try:
            trigger()
        except HyperspaceException:
            assert committed
        assert (
            log_mgr.get_latest_log().state in States.STABLE_STATES
        )
        assert_serve_matches_source(s, src)


class TestSidecarPublishCrash:
    """mid_sidecar_publish: a crash between computing the aggregate-plane
    sidecar and its atomic replace (indexes/aggindex.capture_index_dir).
    SimulatedCrash is a BaseException, so it must propagate through
    capture_safely's Exception swallow, fail the surrounding op(), and
    recovery must roll the action back; the retried action completes with
    a COMPLETE sidecar (the publish is atomic — never a torn one)."""

    def test_create_crashed_at_sidecar_publish_recovers(self, env):
        import json

        from hyperspace_tpu.indexes import aggindex

        s, hs, src = env
        df = s.read.parquet(src)
        cfg = CoveringIndexConfig("idx", ["clicks"], ["query"])
        log_mgr, _ = s.index_manager._managers("idx")
        faults.set_crash("mid_sidecar_publish", "raise;match=_aggstate")
        with pytest.raises(SimulatedCrash):
            hs.create_index(df, cfg)
        assert faults.stats().get("crash.mid_sidecar_publish", 0) == 1
        assert log_mgr.get_latest_log().state not in States.STABLE_STATES
        wait_lease()
        rep = hs.recover("idx")
        assert rep["rolled_back"]
        # retry: completes, and the published sidecar parses (atomic —
        # the crash could only ever leave it absent, never torn)
        hs.create_index(s.read.parquet(src), cfg)
        tip = log_mgr.get_latest_log()
        assert tip.state == States.ACTIVE
        found = []
        for root, _dirs, names in os.walk(log_mgr.index_path):
            for n in names:
                if n == aggindex.SIDECAR_NAME:
                    found.append(os.path.join(root, n))
        assert found, "sidecar missing after the retried create"
        for p in found:
            with open(p, "r", encoding="utf-8") as fh:
                assert json.load(fh).get("files")
        assert_serve_matches_source(s, src)


class TestQuerylogRotateCrash:
    """mid_querylog_rotate (obs/querylog.py): a crash between the active
    segment's fsync and the sealed-segment rename. The record that
    triggered the rotation is already durable, so recovery = nothing to
    repair: the next writer (its own per-process tag) simply appends
    alongside, and the reader unions active + sealed files of every
    incarnation — zero loss, zero duplicates, every row schema-valid."""

    def test_crash_mid_rotate_loses_nothing(self, tmp_path):
        from hyperspace_tpu.obs import querylog as ql

        d = str(tmp_path / "obslog")

        def rec(tag, i):
            return {
                "fingerprint": f"{tag}{i}",
                "duration_s": 0.01,
                "status": "ok",
                "stages": {"scan": 0.001},
                "rows_returned": i,
            }

        faults.set_crash("mid_querylog_rotate", "raise")
        log = ql.QueryLog(d, max_bytes=256, max_files=64)
        written = 0
        crashed = False
        try:
            for i in range(64):
                assert log.append(rec("a", i))
                written += 1
        except SimulatedCrash:
            crashed = True
            written += 1  # the rotating append was durable pre-crash
        assert crashed, "rotation never crossed the crash seam"
        assert faults.stats().get("crash.mid_querylog_rotate", 0) == 1
        # recovery: a fresh incarnation (new process/pid) keeps writing;
        # the un-sealed active file from the crashed writer still reads
        log2 = ql.QueryLog(d, max_bytes=1 << 20, max_files=64)
        for i in range(5):
            assert log2.append(rec("b", i))
        log2.close()
        records = ql.read_records(d)
        fps = [r["fingerprint"] for r in records]
        assert len([f for f in fps if f.startswith("a")]) == written
        assert len([f for f in fps if f.startswith("b")]) == 5
        assert len(set(fps)) == len(fps), "duplicate records after crash"
        for r in records:
            assert ql.validate_record(r) is None, r

    def test_rotation_bounds_hold_without_crash(self, tmp_path):
        from hyperspace_tpu.obs import querylog as ql

        d = str(tmp_path / "obslog")
        log = ql.QueryLog(d, max_bytes=256, max_files=2)
        for i in range(200):
            assert log.append(
                {
                    "fingerprint": f"f{i}",
                    "duration_s": 0.01,
                    "status": "ok",
                    "stages": {},
                    "rows_returned": i,
                }
            )
        log.close()
        assert log.rotations > 2
        sealed = [
            n
            for n in os.listdir(d)
            if n.endswith(".sealed.jsonl")
        ]
        assert len(sealed) <= 2  # maxFiles bound
        # the survivors replay cleanly (bounded retention, never torn)
        for r in ql.read_records(d):
            assert ql.validate_record(r) is None, r


# ---------------------------------------------------------------------------
# Cancel: direct coverage (satellite)
# ---------------------------------------------------------------------------


class TestCancelDirect:
    @pytest.mark.parametrize(
        "transient",
        sorted(States.ROLLBACK),
    )
    def test_cancel_each_transient_state(self, env, transient):
        s, hs, src = env
        df = s.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        # move the stable base to what the transient state implies
        expect = States.ROLLBACK[transient]
        if expect == States.DELETED:
            hs.delete_index("idx")
        log_mgr, _ = s.index_manager._managers("idx")
        stable = log_mgr.get_latest_stable_log()
        stranded = stable.with_state(transient)
        recovery.stamp_lease(stranded, "dead", 60_000)
        assert log_mgr.write_log(log_mgr.get_latest_id() + 1, stranded)
        s.index_manager.clear_cache()
        # cancel is the OPERATOR override: it does not wait for the
        # lease to expire
        hs.cancel("idx")
        tip = log_mgr.get_latest_log()
        if expect == States.DOESNOTEXIST:
            # cancel appends a copy of the LAST STABLE entry — for a
            # stranded CREATING over an index with stable history that
            # is the ACTIVE entry, not the ROLLBACK-map default (the
            # no-history case is test_cancel_of_failed_first_create)
            assert tip.state == States.ACTIVE
        else:
            assert tip.state == expect
        assert recovery.LEASE_OWNER_PROP not in tip.properties

    def test_cancel_of_failed_first_create(self, env):
        s, hs, src = env
        from hyperspace_tpu.actions import create as create_mod

        def boom(self):
            raise RuntimeError("op died")

        orig = create_mod.CreateAction.op
        create_mod.CreateAction.op = boom
        try:
            with pytest.raises(RuntimeError):
                hs.create_index(
                    s.read.parquet(src),
                    CoveringIndexConfig("idx", ["clicks"]),
                )
        finally:
            create_mod.CreateAction.op = orig
        log_mgr, _ = s.index_manager._managers("idx")
        assert log_mgr.get_latest_log().state == States.CREATING
        hs.cancel("idx")
        assert log_mgr.get_latest_log().state == States.DOESNOTEXIST
        # name reusable right away
        hs.create_index(
            s.read.parquet(src), CoveringIndexConfig("idx", ["clicks"])
        )

    def test_cancel_losing_commit_race_raises(self, env, monkeypatch):
        """When the live writer's end-commit wins the id cancel wanted,
        cancel must NOT report success — the tip is stable, but it is
        the opposite of a cancellation."""
        s, hs, src = env
        df = s.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        log_mgr, _ = s.index_manager._managers("idx")
        stable = log_mgr.get_latest_stable_log()
        tip = log_mgr.get_latest_id() + 1
        busy = stable.with_state(States.REFRESHING)
        recovery.stamp_lease(busy, "live", 60_000)
        assert log_mgr.write_log(tip, busy)
        from hyperspace_tpu.actions.cancel import CancelAction

        real_write = log_mgr.write_log
        committed = stable.copy()

        def writer_sneaks_in(log_id, entry):
            # the writer's end-commit lands just before cancel's write
            if log_id == tip + 1 and not getattr(writer_sneaks_in, "done", 0):
                writer_sneaks_in.done = 1
                real_write(tip + 1, committed)
            return real_write(log_id, entry)

        monkeypatch.setattr(log_mgr, "write_log", writer_sneaks_in)
        with pytest.raises(ConcurrentWriteException):
            CancelAction(s, "idx", log_mgr).run()

    def test_cancel_clears_torn_tip(self, env):
        """cancel() is the manual override even with auto-recovery off:
        a torn (truncated-JSON) tip must be cancellable, not wedge the
        index behind a LogCorruptedError."""
        s, hs, src = env
        s.conf.set(C.RECOVERY_ENABLED, False)
        df = s.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        log_mgr, _ = s.index_manager._managers("idx")
        tip = log_mgr.get_latest_id() + 1
        with open(log_mgr._path_for(tip), "w") as f:
            f.write('{"state": "REFRESH')
        hs.cancel("idx")
        assert log_mgr.get_latest_log().state == States.ACTIVE

    def test_cancel_racing_live_writer_lease(self, env, monkeypatch):
        """Cancel vs a LIVE writer: cancel wins the rollback id, the
        writer's end-commit loses the OCC race and aborts — never both."""
        s, hs, src = env
        df = s.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        append_file(src)
        from hyperspace_tpu.actions import refresh as refresh_mod

        in_op = threading.Event()
        release = threading.Event()
        orig_op = refresh_mod.RefreshAction.op

        def gated_op(self):
            in_op.set()
            assert release.wait(10)
            return orig_op(self)

        monkeypatch.setattr(refresh_mod.RefreshAction, "op", gated_op)
        errors = []

        def run_refresh():
            try:
                hs.refresh_index("idx", "full")
            except Exception as exc:
                errors.append(exc)

        t = threading.Thread(target=run_refresh)
        t.start()
        assert in_op.wait(10)
        log_mgr, _ = s.index_manager._managers("idx")
        live = log_mgr.get_latest_log()
        assert live.state == States.REFRESHING
        assert not recovery.is_stranded(live, 60_000)  # lease is live
        hs.cancel("idx")  # operator override
        release.set()
        t.join(30)
        assert len(errors) == 1
        assert isinstance(errors[0], ConcurrentWriteException)
        assert log_mgr.get_latest_log().state == States.ACTIVE


# ---------------------------------------------------------------------------
# base_id TOCTOU (satellite): snapshot at run(), not __init__
# ---------------------------------------------------------------------------


class TestBaseIdResnapshot:
    def test_queued_action_does_not_clobber(self, env):
        s, hs, src = env
        df = s.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        log_mgr, _ = s.index_manager._managers("idx")
        from hyperspace_tpu.actions.delete import DeleteAction

        queued = DeleteAction(s, "idx", log_mgr)
        stale_base = queued.base_id
        # the log advances while the action sits in a queue
        append_file(src)
        hs.refresh_index("idx", "full")
        assert log_mgr.get_latest_id() == stale_base + 2
        queued.run()  # must re-snapshot, not write at stale_base + 1
        assert queued.base_id == stale_base + 2
        tip = log_mgr.get_latest_log()
        assert tip.state == States.DELETED
        assert tip.id == stale_base + 4

    def test_occ_loser_retries_from_fresh_snapshot(self, env, monkeypatch):
        """An action whose begin write collides retries against the new
        tip instead of surfacing ConcurrentWriteException."""
        s, hs, src = env
        df = s.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        log_mgr, _ = s.index_manager._managers("idx")
        from hyperspace_tpu.actions.delete import DeleteAction, RestoreAction

        # simulate the interleaving: another writer's FULL delete lands
        # between our snapshot and our begin write, exactly once
        real_write = log_mgr.write_log
        fired = {}

        def racing_write(log_id, entry):
            if not fired:
                fired["x"] = True
                DeleteAction(s, "idx", log_mgr).run()  # rival wins first
            return real_write(log_id, entry)

        monkeypatch.setattr(log_mgr, "write_log", racing_write)
        action = DeleteAction(s, "idx", log_mgr)
        with pytest.raises(HyperspaceException, match="requires state"):
            # retry DOES re-validate: the rival delete moved the index
            # to DELETED, so our delete is now illegal — typed, precise
            action.run()
        monkeypatch.undo()
        # and an action still legal after the race simply succeeds
        restore = RestoreAction(s, "idx", log_mgr)
        restore.run()
        assert log_mgr.get_latest_log().state == States.ACTIVE


# ---------------------------------------------------------------------------
# Subprocess (true torn state): the process REALLY dies mid-protocol
# ---------------------------------------------------------------------------


CHILD_TEMPLATE = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
from hyperspace_tpu.session import HyperspaceSession
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu import constants as C
from hyperspace_tpu.testing import faults

s = HyperspaceSession()
s.conf.set(C.INDEX_SYSTEM_PATH, {index_root!r})
s.conf.set(C.INDEX_NUM_BUCKETS, 8)
s.conf.set(C.RECOVERY_LEASE_MS, {lease!r})
hs = Hyperspace(s)
faults.set_crash({point!r}, "exit")
{body}
raise SystemExit(7)  # must never get here: the crash point exits first
"""


@pytest.mark.slow
class TestSubprocessCrash:
    def _run_child(self, body, index_root, point):
        code = CHILD_TEMPLATE.format(
            index_root=index_root, point=point, lease=LEASE_MS, body=body
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        assert proc.returncode == faults.CRASH_EXIT_CODE, (
            proc.returncode,
            proc.stdout[-2000:],
            proc.stderr[-2000:],
        )

    @pytest.mark.parametrize("point", ["mid_data_write", "after_begin_log"])
    def test_child_create_killed_then_recovered(self, env, tmp_path, point):
        s, hs, src = env
        index_root = s.conf.get(C.INDEX_SYSTEM_PATH)
        body = (
            f"df = s.read.parquet({src!r})\n"
            "hs.create_index(df, CoveringIndexConfig('idx', ['clicks'], "
            "['query']))"
        )
        self._run_child(body, index_root, point)
        log_mgr, _ = s.index_manager._managers("idx")
        assert log_mgr.get_latest_log().state == States.CREATING
        wait_lease()
        rep = hs.recover("idx")
        assert rep["rolled_back"]
        assert log_mgr.get_latest_log().state == States.DOESNOTEXIST
        assert recovery.find_orphans(log_mgr.index_path) == []
        # name reusable: the retried create completes in THIS process
        hs.create_index(
            s.read.parquet(src),
            CoveringIndexConfig("idx", ["clicks"], ["query"]),
        )
        assert_serve_matches_source(s, src)

    def test_child_refresh_killed_after_end_log(self, env):
        s, hs, src = env
        df = s.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        append_file(src)
        index_root = s.conf.get(C.INDEX_SYSTEM_PATH)
        body = "hs.refresh_index('idx', 'full')"
        self._run_child(body, index_root, "after_end_log")
        log_mgr, _ = s.index_manager._managers("idx")
        tip_id = log_mgr.get_latest_id()
        # committed but unpublished: the pointer lags the tip
        assert log_mgr.get_latest_stable_pointer_id() != tip_id
        rep = hs.recover("idx")
        assert rep["healed_pointer"]
        assert log_mgr.get_latest_stable_pointer_id() == tip_id
        assert recovery.find_orphans(log_mgr.index_path) == []
        assert_serve_matches_source(s, src)


# ---------------------------------------------------------------------------
# Durable cross-process pins (fleet mode, docs/fleet-serve.md): a pin
# registered by process A must survive a GC/vacuum driven from process B
# until A's lease expires; expired pins are reaped and the file set
# converges.
# ---------------------------------------------------------------------------


class TestCrossProcessPins:
    def _mk_index(self, env):
        s, hs, src = env
        df = s.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("idx", ["clicks"], ["query"]))
        log_mgr, _ = s.index_manager._managers("idx")
        return s, hs, src, log_mgr

    def _as_process_b(self, monkeypatch):
        """Simulate the GC/vacuum running in ANOTHER process: process
        B's in-memory pin registry is empty — only the durable pin
        files on disk can speak for A's live queries."""
        monkeypatch.setattr(recovery, "_active_pins", {})

    def test_pin_file_published_and_released(self, env):
        s, hs, src, log_mgr = self._mk_index(env)
        entries = s.index_manager.get_indexes([States.ACTIVE])
        token = recovery.register_pins(entries, durable=True, lease_ms=5_000)
        pins_dir = os.path.join(log_mgr.index_path, C.HYPERSPACE_PINS_DIR)
        names = os.listdir(pins_dir)
        assert len(names) == 1 and names[0].endswith(".json")
        assert recovery.durable_pinned_files(log_mgr.index_path) == {
            p.replace("\\", "/") for p in entries[0].content.files
        }
        recovery.release_pins(token)
        assert recovery.durable_pinned_files(log_mgr.index_path) == set()
        assert not os.path.isdir(pins_dir) or not os.listdir(pins_dir)

    def test_gc_from_process_b_respects_live_pin(self, env, monkeypatch):
        s, hs, src, log_mgr = self._mk_index(env)
        index_path = log_mgr.index_path
        # strand an orphan and pin it durably, as process A's live
        # query would
        orphan_dir = os.path.join(index_path, "v__=9")
        os.makedirs(orphan_dir)
        orphan = os.path.join(orphan_dir, "part-orphan.parquet")
        with open(orphan, "w") as f:
            f.write("x")
        from hyperspace_tpu.metadata.entry import Content

        entry = log_mgr.get_latest_stable_log().copy()
        entry.content = Content.from_leaf_files([(orphan, 1, 1)])
        token = recovery.register_pins(
            [entry], durable=True, lease_ms=60_000, heartbeat=False
        )
        self._as_process_b(monkeypatch)
        rep = recovery.gc_orphans(index_path, grace_ms=0)
        assert rep["kept_pinned"] == 1 and os.path.isfile(orphan)
        assert rep["reaped_pins"] == 0
        # A's lease expires (its heartbeat died with it): the pin file
        # is reaped and the file set converges on the next pass
        rep = recovery.gc_orphans(
            index_path, grace_ms=0, now=recovery.now_ms() + 120_000
        )
        assert rep["reaped_pins"] == 1
        assert rep["quarantined_dirs"] == 1
        assert not os.path.exists(orphan)
        pins_dir = os.path.join(index_path, C.HYPERSPACE_PINS_DIR)
        assert not os.path.isdir(pins_dir) or not os.listdir(pins_dir)
        # convergence: a further pass finds nothing
        rep = recovery.gc_orphans(index_path, grace_ms=0)
        assert rep["quarantined_files"] == 0 and rep["quarantined_dirs"] == 0
        recovery.release_pins(token)

    def test_vacuum_from_process_b_respects_live_pin(
        self, env, monkeypatch
    ):
        s, hs, src, log_mgr = self._mk_index(env)
        index_path = log_mgr.index_path
        old_files = set(log_mgr.get_latest_stable_log().content.files)
        # pin the CURRENT (soon-to-be-outdated) version durably, as a
        # mid-serve query in process A would
        entries = s.index_manager.get_indexes([States.ACTIVE])
        token = recovery.register_pins(
            entries, durable=True, lease_ms=60_000, heartbeat=False
        )
        # a full refresh supersedes the pinned version...
        append_file(src)
        hs.refresh_index("idx", "full")
        # ...and process B vacuums the outdated versions
        self._as_process_b(monkeypatch)
        hs.vacuum_index("idx")
        for p in old_files:
            assert os.path.isfile(p), f"vacuum deleted pinned file {p}"
        # A dies (kill -9): its heartbeat stops and the lease runs out —
        # simulated by restamping the pin file already-expired
        import json as _json

        pins_dir = os.path.join(index_path, C.HYPERSPACE_PINS_DIR)
        for name in os.listdir(pins_dir):
            p = os.path.join(pins_dir, name)
            with open(p) as fh:
                doc = _json.load(fh)
            doc["expiresAtMs"] = recovery.now_ms() - 1
            with open(p, "w") as fh:
                _json.dump(doc, fh)
        # B's retried vacuum now deletes the leftovers and reaps the pin
        hs.vacuum_index("idx")
        for p in old_files:
            assert not os.path.exists(p)
        assert not os.path.isdir(pins_dir) or not os.listdir(pins_dir)
        assert recovery.find_orphans(index_path) == []
        assert_serve_matches_source(s, src)
        recovery.release_pins(token)

    def test_heartbeat_keeps_pin_alive(self, env):
        s, hs, src, log_mgr = self._mk_index(env)
        entries = s.index_manager.get_indexes([States.ACTIVE])
        token = recovery.register_pins(entries, durable=True, lease_ms=60)
        pins_dir = os.path.join(log_mgr.index_path, C.HYPERSPACE_PINS_DIR)

        def pin_names():
            # a listdir can race the heartbeat's fsync-before-replace
            # and see its transient .tmp_* file; only published pin
            # files are the contract
            return [
                n for n in os.listdir(pins_dir)
                if not n.startswith(".tmp_")
            ]

        name = pin_names()[0]
        # several lease periods later the file is still unexpired: the
        # heartbeat has been renewing it
        time.sleep(0.25)
        assert recovery.durable_pinned_files(log_mgr.index_path)
        assert pin_names() == [name]
        recovery.release_pins(token)

    def test_torn_pin_file_is_reaped(self, env):
        s, hs, src, log_mgr = self._mk_index(env)
        pins_dir = os.path.join(log_mgr.index_path, C.HYPERSPACE_PINS_DIR)
        os.makedirs(pins_dir, exist_ok=True)
        with open(os.path.join(pins_dir, "dead.1.json"), "w") as f:
            f.write('{"owner": "dead", "expi')  # torn
        assert recovery.durable_pinned_files(log_mgr.index_path) == set()
        assert not os.path.isdir(pins_dir) or not os.listdir(pins_dir)


class TestSpillWriteCrash:
    """``mid_spill_write``: a demotion killed between choosing the spill
    path and the atomic publish leaves no final ``.spill`` file — at
    most a ``.tmp_spool_`` temp the orphan reaper deletes — so a torn
    spill is never served and the tier heals on the next demote
    (docs/out-of-core.md)."""

    def _batch(self, n=2_000):
        import numpy as np

        rng = np.random.default_rng(11)
        from hyperspace_tpu.io.columnar import ColumnarBatch

        return ColumnarBatch.from_arrow(
            pa.table(
                {
                    "k": rng.integers(0, 50, n).astype(np.int64),
                    "v": rng.normal(0, 1, n),
                }
            )
        )

    def test_crash_mid_spill_write_never_serves_torn_state(self, tmp_path):
        from hyperspace_tpu.execution.serve_cache import (
            ServeCache,
            batch_nbytes,
        )

        spill_dir = tmp_path / C.HYPERSPACE_SPILL_DIR
        batch = self._batch()
        nb = batch_nbytes(batch)
        c = ServeCache(
            max_bytes=nb + 16,
            spill_dir=str(spill_dir),
            spill_max_bytes=1 << 30,
        )
        c.put(("scan", "fp-a", ("k",)), batch, nb)
        faults.set_crash("mid_spill_write", "raise")
        # displacing fp-a pushes its demotion across the crash seam
        with pytest.raises(SimulatedCrash):
            c.put(("zonemap", "fp-b"), "displacer", nb)
        assert faults.stats().get("crash.mid_spill_write", 0) == 1
        faults.reset()
        # no torn final file was published, and the key is a clean miss
        # — the crashed demotion is never served
        if spill_dir.is_dir():
            assert not [
                p for p in os.listdir(spill_dir) if p.endswith(".spill")
            ]
        assert c.spill_paths() == set()
        assert c.get(("scan", "fp-a", ("k",))) is None
        # the reaper clears whatever wreckage remains (ttl=0: everything
        # not indexed by a live cache is expired)
        recovery.reap_spill_orphans(str(tmp_path), ttl_ms=0)
        assert not spill_dir.is_dir() or not os.listdir(spill_dir)
        # the tier heals: a retried demote + restore round-trips
        c.put(("scan", "fp-a", ("k",)), batch, nb)
        c.put(("zonemap", "fp-c"), "displacer", nb)
        assert c.spill_demotes == 1
        restored = c.get(("scan", "fp-a", ("k",)))
        assert restored is not None
        assert restored.to_arrow().equals(batch.to_arrow())
