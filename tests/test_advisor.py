"""Workload advisor (hyperspace_tpu/advisor/, docs/advisor.md).

Five legs, mirroring the ISSUE's acceptance criteria:

* plan specs round-trip: record a plan, rebuild it against the
  session, serve the SAME answer;
* the profile is bounded (maxShapes cap folds into overflow, never
  grows the dict) and per-execution ``rows_pruned`` attribution holds
  (``trace.accumulate`` is root-scoped);
* candidate enumeration mirrors the consuming rules (filter / join /
  aggregate shapes) and what-if scoring uses the REAL rule chain — a
  candidate twin of an existing index gains zero;
* apply is gated, budgeted, and failure-isolated;
* the closed loop converges end to end: skewed workload -> profile ->
  recommend the known-best covering index -> apply under budget ->
  replayed p50 improves -> second pass recommends nothing.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.advisor import (
    advise,
    apply_recommendations,
    build_profile,
    hypothetical_entry,
    score_workload,
)
from hyperspace_tpu.advisor import recommend as rec_mod
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.obs import planspec, trace
from hyperspace_tpu.testing import replay


@pytest.fixture(autouse=True)
def _obs_isolation():
    trace.reset()
    yield
    trace.set_enabled(False)
    trace.reset()


def _lake(tmp_path, rows=40_000, files=4, name="lake"):
    d = tmp_path / name
    d.mkdir()
    rng = np.random.default_rng(5)
    per = rows // files
    for i in range(files):
        pq.write_table(
            pa.table(
                {
                    "key": rng.integers(0, 1000, per),
                    "ts": np.arange(i * per, (i + 1) * per, dtype=np.int64),
                    "payload": rng.integers(0, 1 << 30, per),
                }
            ),
            str(d / f"part-{i:03d}.parquet"),
        )
    return str(d)


class TestPlanSpec:
    def test_round_trip_serves_same_answer(self, session_factory, tmp_path):
        s = session_factory(1)
        data = _lake(tmp_path)
        df = s.read.parquet(data)
        q = df.filter(df["key"] == 7).select("key", "payload")
        spec = planspec.to_spec(q.logical_plan)
        assert spec is not None and spec["spec_v"] == planspec.SPEC_V
        from hyperspace_tpu.dataframe import DataFrame

        rebuilt = planspec.from_spec(s, spec)
        a = DataFrame(s, q.logical_plan).to_arrow()
        b = DataFrame(s, rebuilt).to_arrow()
        assert a.num_rows == b.num_rows
        assert a.column("payload").to_pylist() == b.column("payload").to_pylist()

    def test_unsupported_plan_records_no_spec(self, session_factory, tmp_path):
        s = session_factory(1)
        data = _lake(tmp_path)
        df = s.read.parquet(data)
        other = s.read.parquet(data)
        union = df.union(other) if hasattr(df, "union") else None
        if union is not None:
            assert planspec.to_spec(union.logical_plan) is None

    def test_unknown_spec_version_raises(self, session_factory):
        s = session_factory(1)
        with pytest.raises(HyperspaceException):
            planspec.from_spec(s, {"op": "scan", "fmt": "parquet",
                                   "paths": ["/x"], "spec_v": 99})


class TestProfile:
    def test_shape_cap_folds_into_overflow(self):
        recs = [
            {"predicate": f"shape{i}", "duration_s": 0.01, "status": "ok",
             "ts_ms": i}
            for i in range(10)
        ]
        prof = build_profile(recs, max_shapes=4)
        assert len(prof.shapes) == 4
        assert prof.overflow_records == 6
        assert prof.records == 10

    def test_hot_shapes_rank_by_cost_then_count(self):
        recs = (
            [{"predicate": "cheap", "duration_s": 0.001, "status": "ok"}] * 5
            + [{"predicate": "hot", "duration_s": 1.0, "status": "ok"}] * 2
        )
        prof = build_profile(recs)
        assert prof.hot_shapes(1)[0].shape == "hot"

    def test_degrade_retry_and_stage_aggregation(self):
        rec = {
            "predicate": "p", "duration_s": 0.5, "status": "failed",
            "stages": {"scan": 0.1, "prune": 0.02},
            "events": [{"name": "degrade"}, {"name": "retry"}],
            "indexes": ["idx1"], "slo_class": "batch", "rows_pruned": 7,
        }
        prof = build_profile([rec, dict(rec)])
        s = prof.shapes["p"]
        assert s.degrades == 2 and s.retries == 2 and s.failed == 2
        assert s.stages["scan"] == pytest.approx(0.2)
        assert s.indexes == {"idx1": 2}
        assert s.rows_pruned == 14

    def test_accumulate_is_root_scoped(self):
        """Satellite 1: rows_pruned attributes to the EXECUTING query's
        root, so two queries pruning different amounts never blur."""
        trace.set_enabled(True)
        r1 = trace.root("serve.query")
        with trace.activate(r1):
            trace.accumulate("rows_pruned", 5)
            trace.accumulate("rows_pruned", 2)
        r1.finish()
        r2 = trace.root("serve.query")
        with trace.activate(r2):
            trace.accumulate("rows_pruned", 3)
        r2.finish()
        assert r1.attrs["rows_pruned"] == 7
        assert r2.attrs["rows_pruned"] == 3


class TestWhatIf:
    def test_candidate_gain_positive_then_zero_once_real(
        self, session_factory, tmp_path
    ):
        s = session_factory(1)
        data = _lake(tmp_path)
        df = s.read.parquet(data)
        plan = df.filter(df["key"] == 3).select("key", "payload").logical_plan
        cands = rec_mod.enumerate_candidates(plan)
        assert [c.config.indexed_columns for c in cands] == [["key"]]
        hypo = hypothetical_entry(s, df, cands[0].config)
        out = score_workload(s, [(plan, 1.0)], [], hypo)
        assert out["gain"] > 0 and out["plans_improved"] == 1
        # build the real twin: the hypothetical stops adding anything
        Hyperspace(s).create_index(
            df,
            CoveringIndexConfig(
                cands[0].config.index_name,
                list(cands[0].config.indexed_columns),
                list(cands[0].config.included_columns),
            ),
        )
        active = s.index_manager.get_indexes([States.ACTIVE])
        out2 = score_workload(s, [(plan, 1.0)], active, hypo)
        assert out2["gain"] == 0

    def test_join_and_agg_candidates(self, session_factory, tmp_path):
        s = session_factory(1)
        data = _lake(tmp_path)
        other = tmp_path / "orders"
        other.mkdir()
        rng = np.random.default_rng(9)
        pq.write_table(
            pa.table(
                {
                    "okey": rng.integers(0, 1000, 4_000),
                    "cost": rng.integers(0, 100, 4_000),
                }
            ),
            str(other / "part-000.parquet"),
        )
        left = s.read.parquet(data).select("key", "payload")
        right = s.read.parquet(str(other))
        jp = left.join(right, left["key"] == right["okey"]).logical_plan
        cands = rec_mod.enumerate_candidates(jp)
        kinds = {tuple(c.config.indexed_columns) for c in cands}
        assert kinds == {("key",), ("okey",)}  # one per join side
        from hyperspace_tpu.plan.nodes import AggSpec

        ap = (
            s.read.parquet(data)
            .group_by("key")
            .agg(AggSpec("sum", "payload", "total"))
            .logical_plan
        )
        acands = rec_mod.enumerate_candidates(ap)
        assert [c.config.indexed_columns for c in acands] == [["key"]]


class TestApply:
    def test_apply_requires_opt_in(self, session_factory, tmp_path):
        s = session_factory(1)
        with pytest.raises(HyperspaceException):
            apply_recommendations(s, [])
        s.conf.set(C.ADVISOR_APPLY_ENABLED, True)
        assert apply_recommendations(s, [])["applied"] == 0

    def test_byte_budget_skips_but_later_cheaper_fit(
        self, session_factory, tmp_path
    ):
        s = session_factory(1)
        data = _lake(tmp_path, rows=4_000, files=2)
        df = s.read.parquet(data)
        plan = df.filter(df["key"] == 1).select("key", "payload").logical_plan
        cand = rec_mod.enumerate_candidates(plan)[0]

        def mk(name, est):
            return rec_mod.Recommendation(
                kind="create", index_name=name, index_kind="CoveringIndex",
                indexed_columns=list(cand.config.indexed_columns),
                included_columns=list(cand.config.included_columns),
                source_paths=list(cand.source_paths),
                estimated_benefit_s=1.0, estimated_build_bytes=est,
                score_gain=1.0, shapes=[], reason="test",
            )

        out = apply_recommendations(
            s, [mk("adv_big", 10_000), mk("adv_small", 10)],
            max_bytes=100, force=True,
        )
        by = {o["index"]: o["outcome"] for o in out["outcomes"]}
        assert by == {"adv_big": "skipped", "adv_small": "applied"}
        names = {e.name for e in s.index_manager.get_indexes([States.ACTIVE])}
        assert "adv_small" in names and "adv_big" not in names

    def test_failures_do_not_abort_the_pass(self, session_factory, tmp_path):
        s = session_factory(1)
        bad = rec_mod.Recommendation(
            kind="refresh", index_name="nope", index_kind="CoveringIndex",
            indexed_columns=["key"], included_columns=[], source_paths=[],
            estimated_benefit_s=1.0, estimated_build_bytes=0,
            score_gain=0.0, shapes=[], reason="test",
        )
        out = apply_recommendations(s, [bad, bad], force=True)
        assert out["failed"] == 2 and out["applied"] == 0


class TestReplay:
    def test_records_without_spec_are_counted_skipped(
        self, session_factory, tmp_path
    ):
        s = session_factory(1)
        s.enable_hyperspace()
        data = _lake(tmp_path, rows=4_000, files=2)
        recs = replay.skewed_keys([data], "key", [1, 2, 3], 4)
        bare = {k: v for k, v in recs[0].items() if k != "replay"}
        result = replay.replay_records(s, recs + [bare])
        assert result.submitted == 4
        assert result.completed == 4
        assert result.skipped == 1
        assert replay.last_replay_stats["completed"] == 4

    def test_slo_classes_flow_to_admission(self, session_factory, tmp_path):
        s = session_factory(1)
        s.enable_hyperspace()
        data = _lake(tmp_path, rows=4_000, files=2)
        recs = replay.tenant_mix(
            [data], "key", [1, 2], {"interactive": 3, "batch": 2}
        )
        result = replay.replay_records(s, recs)
        assert result.completed == 5
        stats = s.serve_frontend.stats()
        classes = stats.get("classes") or {}
        if classes:  # fleet class accounting present in this build
            assert set(classes) >= {"interactive", "batch"}

    def test_preserve_timing_respects_gaps(self, session_factory, tmp_path):
        import time as _time

        s = session_factory(1)
        s.enable_hyperspace()
        data = _lake(tmp_path, rows=4_000, files=2)
        recs = replay.skewed_keys(
            [data], "key", [1], 3, start_ts_ms=0, interarrival_ms=120
        )
        t0 = _time.perf_counter()
        replay.replay_records(s, recs, preserve_timing=True)
        assert _time.perf_counter() - t0 >= 0.24  # two recorded gaps

    def test_record_workload_round_trips_reader(self, tmp_path):
        from hyperspace_tpu.obs import querylog

        recs = replay.rolling_appends(["/x"], "ts", [1, 2], 2)
        d = str(tmp_path / "obs")
        assert replay.record_workload(recs, d) == len(recs)
        got = querylog.read_valid_records(d)
        assert len(got) == len(recs)
        for r in got:
            assert querylog.validate_record(r) is None


class TestConvergence:
    def test_closed_loop_improves_p50_then_recommends_nothing(
        self, session_factory, tmp_path
    ):
        s = session_factory(1)
        data = _lake(tmp_path, rows=2_000_000, files=8)
        keys = list(range(0, 1000, 37))
        records = replay.skewed_keys(
            [data], "key", keys, 16, project=["key", "payload"]
        )
        obs_dir = str(tmp_path / "obs")
        replay.record_workload(records, obs_dir)
        s.enable_hyperspace()

        baseline = replay.replay_records(s, records)
        assert baseline.completed == len(records)

        report = advise(s, directory=obs_dir)
        creates = [r for r in report.recommendations if r.kind == "create"]
        assert creates, "the skewed workload must motivate an index"
        top = creates[0]
        assert top.indexed_columns[0] == "key"
        assert top.index_kind == "CoveringIndex"
        assert top.estimated_benefit_s > 0

        summary = apply_recommendations(s, creates, force=True)
        assert summary["applied"] >= 1

        after = replay.replay_records(s, records)
        assert after.completed == len(records)
        assert after.p50_s < baseline.p50_s, (
            baseline.to_dict(), after.to_dict()
        )

        report2 = advise(s, directory=obs_dir)
        assert [
            r for r in report2.recommendations if r.kind == "create"
        ] == [], "second pass must converge to zero create recommendations"


class TestCli:
    def test_report_and_recommend(self, tmp_path, capsys, session_factory):
        from hyperspace_tpu.advisor import cli

        data = _lake(tmp_path, rows=4_000, files=2)
        recs = replay.skewed_keys(
            [data], "key", [1, 2, 3], 6, project=["key", "payload"]
        )
        obs_dir = str(tmp_path / "obs")
        replay.record_workload(recs, obs_dir)
        assert cli.main(["report", "--log-dir", obs_dir]) == 0
        out = capsys.readouterr().out
        assert "records=6" in out and "replay=y" in out
        assert (
            cli.main(
                ["recommend", "--log-dir", obs_dir,
                 "--system-path", str(tmp_path / "idx")]
            )
            == 0
        )
        out = capsys.readouterr().out
        assert "recommendations" in out
