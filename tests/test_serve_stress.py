"""64-client serve stress (slow; run by scripts/bench_smoke.sh / nightly).

The acceptance criteria of ISSUE 8 at full scale: 64 concurrent clients
with ``refresh`` running concurrently return results bit-identical to
serial execution, and the ServeCache never exceeds its configured byte
budget — probed continuously while the storm runs, not just at the end.
Tier-1 keeps the smaller, faster versions (tests/test_serve_frontend.py,
tests/test_serve_cache.py); these rungs exist to surface contention
bugs that only appear past the thread-pool and LRU churn thresholds.
"""

import os
import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import functions as hsf
from hyperspace_tpu.constants import States
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.serve import ServeFrontend

pytestmark = pytest.mark.slow

CLIENTS = 64


@pytest.fixture
def s1(session_factory):
    return session_factory(1)


def _write_rows(path, n, seed, key_hi=2_000):
    rng = np.random.default_rng(seed)
    t = pa.table(
        {
            "k": pa.array(rng.integers(0, key_hi, n), pa.int64()),
            "q": pa.array(rng.integers(1, 50, n), pa.int64()),
            "v": pa.array(rng.normal(0.0, 1.0, n)),
        }
    )
    pq.write_table(t, path)


class TestSixtyFourClients:
    def test_64_clients_budgeted_cache_bit_identical(self, s1, tmp_path):
        """Fixed snapshot, 64 clients, a DELIBERATELY small cache budget
        (forces continuous LRU churn): every result equals its serial
        baseline and the budget holds at every probe."""
        d = tmp_path / "src"
        d.mkdir()
        for i in range(4):
            _write_rows(str(d / f"p{i}.parquet"), 30_000, i)
        s1.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
        hs = Hyperspace(s1)
        df = s1.read.parquet(str(d))
        hs.create_index(df, CoveringIndexConfig("i1", ["k"], ["q", "v"]))
        s1.enable_hyperspace()
        keys = list(range(0, 2_000, 37))
        baseline = {
            k: s1.execute(
                df.filter(df["k"] == k).select("q", "v").logical_plan
            )
            for k in keys
        }
        # small budget: big enough for a few entries, far too small for
        # all of them — the governor must evict, not overflow
        s1.conf.set(C.SERVE_CACHE_ENABLED, True)
        s1.conf.set(C.SERVE_CACHE_MAX_BYTES, 2 << 20)
        cache = s1.serve_cache
        fe = ServeFrontend(s1)
        errors = []
        budget_violations = []
        stop = threading.Event()

        def prober():
            while not stop.is_set():
                if cache.resident_bytes > cache.max_bytes:
                    budget_violations.append(cache.resident_bytes)

        def client(i):
            try:
                for j in range(8):
                    k = keys[(i * 5 + j) % len(keys)]
                    out = fe.serve(df.filter(df["k"] == k).select("q", "v"))
                    assert out.equals(baseline[k]), k
            except Exception as exc:
                errors.append(exc)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(CLIENTS)
        ] + [threading.Thread(target=prober)]
        try:
            for t in threads:
                t.start()
            for t in threads[:-1]:
                t.join(300)
            stop.set()
            threads[-1].join(30)
            assert not errors, errors[:3]
            assert not budget_violations, budget_violations[:5]
            st = cache.stats()
            assert st["high_water_bytes"] <= st["max_bytes"]
            fes = fe.stats()
            assert fes["failed"] == 0
            assert fes["completed"] + fes["deduped"] >= CLIENTS * 8
        finally:
            stop.set()
            fe.close()
            s1.conf.set(C.SERVE_CACHE_ENABLED, False)
            s1.clear_serve_cache()

    def test_64_clients_obs_parent_child_integrity(self, s1, tmp_path):
        """The ISSUE 15 rung: the full 64-client storm with tracing ON.
        Every execution yields exactly ONE root span whose child spans
        all chain to it (no cross-trace leakage through the shared scan
        pool), the querylog row count equals executions, and results
        stay bit-identical to serial."""
        from hyperspace_tpu.obs import querylog, trace

        d = tmp_path / "src"
        d.mkdir()
        for i in range(4):
            _write_rows(str(d / f"p{i}.parquet"), 30_000, i)
        hs = Hyperspace(s1)
        df = s1.read.parquet(str(d))
        hs.create_index(df, CoveringIndexConfig("i1", ["k"], ["q", "v"]))
        s1.enable_hyperspace()
        keys = list(range(0, 2_000, 37))
        baseline = {
            k: s1.execute(
                df.filter(df["k"] == k).select("q", "v").logical_plan
            )
            for k in keys
        }
        s1.conf.set(C.OBS_ENABLED, True)
        s1.conf.set(C.OBS_TRACE_RETAIN, 4096)
        trace.reset()
        fe = ServeFrontend(s1)
        errors = []

        def client(i):
            try:
                for j in range(8):
                    k = keys[(i * 5 + j) % len(keys)]
                    out = fe.serve(df.filter(df["k"] == k).select("q", "v"))
                    assert out.equals(baseline[k]), k
            except Exception as exc:
                errors.append(exc)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(CLIENTS)
        ]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(300)
            assert not errors, errors[:3]
            stats = fe.stats()
        finally:
            fe.close()
            trace.set_enabled(False)
        assert stats["failed"] == 0
        assert stats["completed"] + stats["deduped"] == CLIENTS * 8
        roots = trace.finished("serve.query")
        # one root per EXECUTION (dedup shares the winner's trace)
        assert len(roots) == stats["completed"]
        seen = set()
        for root in roots:
            assert root.trace_id not in seen
            seen.add(root.trace_id)
            by_id = {sp.span_id: sp for sp in root.spans}
            by_id[root.span_id] = root
            for sp in root.spans:
                assert sp.trace_id == root.trace_id
                if sp is root:
                    continue
                hops, cur = 0, sp
                while cur is not root:
                    assert cur.parent_id in by_id, (sp.name, root.trace_id)
                    cur = by_id[cur.parent_id]
                    hops += 1
                    assert hops < 100
            assert root.attrs["status"] == "ok"
        # durable record per execution, every row schema-valid
        records = querylog.read_records(querylog.obs_root(s1.conf))
        assert len(records) == stats["completed"]
        for r in records:
            assert querylog.validate_record(r) is None, r
        trace.reset()

    def test_64_clients_with_concurrent_refresh(self, s1, tmp_path):
        """Appends + incremental refreshes land WHILE 64 clients serve:
        every result is bit-identical to serial execution over the
        source snapshot that query saw, and the index ends ACTIVE."""
        d = tmp_path / "src"
        d.mkdir()
        scratch = tmp_path / "scratch"
        scratch.mkdir()
        for i in range(2):
            _write_rows(str(d / f"p{i}.parquet"), 20_000, i)
        s1.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
        s1.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        s1.conf.set(C.SERVE_CACHE_ENABLED, True)
        s1.conf.set(C.SERVE_CACHE_MAX_BYTES, 64 << 20)
        hs = Hyperspace(s1)
        df0 = s1.read.parquet(str(d))
        hs.create_index(df0, CoveringIndexConfig("i1", ["k"], ["q", "v"]))
        s1.enable_hyperspace()
        fe = ServeFrontend(s1)
        errors = []
        results = []
        res_lock = threading.Lock()

        def agg(df):
            return df.filter((df["k"] >= 100) & (df["k"] < 900)).agg(
                hsf.count().alias("n"), hsf.sum("q").alias("sq")
            )

        def client(i):
            try:
                for j in range(4):
                    df = s1.read.parquet(str(d))
                    files = tuple(df.logical_plan.relation.files)
                    out = fe.serve(agg(df))
                    with res_lock:
                        results.append((files, out))
            except Exception as exc:
                errors.append(exc)

        def writer():
            try:
                for i in range(3):
                    tmp = str(scratch / f"a{i}.parquet")
                    _write_rows(tmp, 2_000, 100 + i)
                    os.rename(tmp, str(d / f"a{i}.parquet"))
                    s1.index_manager.clear_cache()
                    hs.refresh_index("i1", "incremental")
            except Exception as exc:
                errors.append(exc)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(CLIENTS)
        ] + [threading.Thread(target=writer)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(600)
            assert not errors, errors[:3]
            assert fe.stats()["failed"] == 0
            # serial differential per distinct source snapshot
            s1.disable_hyperspace()
            expected = {}
            for files, out in results:
                if files not in expected:
                    dfx = s1.read.parquet(*files)
                    expected[files] = s1.execute(agg(dfx).logical_plan)
                assert out.equals(expected[files]), files
            entry = s1.index_manager.get_index_log_entry("i1")
            assert entry is not None and entry.state == States.ACTIVE
            assert (
                s1.serve_cache.resident_bytes
                <= s1.serve_cache.max_bytes
            )
        finally:
            fe.close()
            s1.conf.set(C.SERVE_CACHE_ENABLED, False)
            s1.clear_serve_cache()
