"""hslint (hyperspace_tpu/analysis) — tier-1 gate + checker self-tests.

Three layers:

* the GATE: the analyzer over the real package must report zero
  unsuppressed findings (every rule violation on the tree is either
  fixed or carries a justified ``# hslint: disable``);
* fixture-based unit tests per checker: a seeded violation is caught,
  a suppression comment silences it, and a clean tree stays clean;
* golden stability: the ruleset and the finding schema are part of the
  repo's contract (CI configs and suppression comments reference rule
  ids), so changing them must be a deliberate act.
"""

import os
import subprocess
import sys
import textwrap

import pytest

import hyperspace_tpu
from hyperspace_tpu.analysis import (
    ALL_RULES,
    CHECKERS,
    FINDING_FIELDS,
    Finding,
    run_analysis,
)

PKG_DIR = os.path.dirname(os.path.abspath(hyperspace_tpu.__file__))
TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _write_tree(root, files):
    for rel, text in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))


def _lint(tmp_path, files, tests=None):
    """Unsuppressed findings for a fixture package tree."""
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    _write_tree(pkg, files)
    tests_dir = None
    if tests is not None:
        tdir = tmp_path / "tests"
        tdir.mkdir(exist_ok=True)
        _write_tree(tdir, tests)
        tests_dir = str(tdir)
    findings = run_analysis(str(pkg), tests_dir=tests_dir)
    return [f for f in findings if not f.suppressed]


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# The gate
# ---------------------------------------------------------------------------


class TestPackageClean:
    def test_no_unsuppressed_findings(self):
        findings = run_analysis(PKG_DIR, tests_dir=TESTS_DIR)
        active = [f for f in findings if not f.suppressed]
        assert not active, "unsuppressed hslint findings:\n" + "\n".join(
            f.render() for f in active
        )

    def test_analyzer_covers_real_surfaces(self):
        """The gate is only meaningful if the checkers engage: the real
        tree must contain native exports, actions, and traced functions
        for them to look at (guards against a silent no-op analyzer)."""
        from hyperspace_tpu.analysis.core import Project
        from hyperspace_tpu.analysis import kernel_parity, log_state, purity

        project = Project(PKG_DIR, tests_dir=TESTS_DIR)
        with open(project.native_cpp_path()) as f:
            exports = kernel_parity.cpp_exports(f.read())
        assert len(exports) >= 5
        machine, _ = log_state._extract_machine(project)
        assert machine.rollback and machine.stable
        traced = [
            fn.name
            for _rel, sf in project.files_under(*purity.HOT_DIRS)
            if sf.tree is not None
            for fn in purity._traced_functions(sf.tree)
        ]
        assert len(traced) >= 5

    def test_shared_state_checker_engages(self):
        """The HS6xx sweep must actually see the concurrency surfaces:
        a populated registry that resolves, thread-pool boundaries, a
        non-trivial reachable set, and written mutable globals."""
        from hyperspace_tpu.analysis.core import Project
        from hyperspace_tpu.analysis import shared_state as ss

        project = Project(PKG_DIR, tests_dir=TESTS_DIR)
        entries, _line = ss.parse_registry(project)
        assert len(entries) >= 10
        idx = ss._PkgIndex(project)
        for e in entries:
            assert idx.resolve_state_path(e.path) is not None, e.path
        checker = ss._Checker(project)
        checker.analyze()
        submits = {t for i in checker.infos.values() for t in i.submits}
        assert len(submits) >= 5, submits  # scan pool, frontend, tails…
        reachable = checker.pool_reachable()
        assert len(reachable) >= 20
        assert len(checker.candidate_globals()) >= 5

    def test_contracts_checker_engages(self):
        """HS7xx must see the config-key and fault-point surfaces."""
        from hyperspace_tpu.analysis.core import Project
        from hyperspace_tpu.analysis import contracts

        project = Project(PKG_DIR, tests_dir=TESTS_DIR)
        keys, defaults, prefixes = contracts._constants_keys(project)
        assert len(keys) >= 20 and len(defaults) >= 20
        assert "hyperspace.faults." in prefixes
        used, _literals = contracts._reads(
            project, {n for n, _l in keys.values()}
        )
        assert len(used) >= 20
        points, _line, _path = contracts._fault_points(project)
        assert set(points) >= {"parquet_read", "kernel_dispatch"}
        assert project.doc_lines(contracts.CONFIG_DOC)
        # the collective-site ↔ dryrun matrix must be live too
        assert project.aux_lines("scripts", contracts.DRYRUN_FILE)

    def test_spmd_checker_engages(self):
        """The HS8xx sweep must actually see the multi-host plane: a
        populated COLLECTIVE_SITES registry that resolves, every
        collective-bearing function registered, and the identity-branch
        scan examining real process-identity sites."""
        from hyperspace_tpu.analysis.core import Project
        from hyperspace_tpu.analysis import spmd

        project = Project(PKG_DIR, tests_dir=TESTS_DIR)
        entries, rel = spmd.parse_sites(project)
        assert rel == "parallel/collectives.py"
        assert len(entries) >= 8
        analysis = spmd._Analysis(project)
        for e in entries:
            assert analysis.resolver.resolve_site_path(e.path) is not None, e.path
        bearing = {
            analysis.site_name(k)
            for k, f in analysis.facts.items()
            if f.primitives
        }
        assert bearing >= {
            "hyperspace_tpu.parallel.shuffle._flat_program",
            "hyperspace_tpu.parallel.shuffle._twostage_program",
            "hyperspace_tpu.parallel.shuffle._twostage_exchange_mp",
            "hyperspace_tpu.indexes.covering_build._global_written",
            "hyperspace_tpu.actions.base._action_rendezvous",
        }
        # every collective-bearing function carries a registry entry
        assert bearing <= {e.path for e in entries}
        # the action protocol's coordinator dispatch is an examined
        # identity branch (the contract HS801 verifies)
        import ast as _ast

        # the protocol body (and its coordinator dispatch) lives in
        # _run_protocol since the obs plane wrapped run() in a root span
        facts = analysis.facts[("actions/base.py", "Action", "_run_protocol")]
        tainted = spmd._identity_tainted_names(facts.node)
        examined = [
            n
            for n in _ast.walk(facts.node)
            if isinstance(n, _ast.If)
            and spmd._expr_has_identity_source(n.test, tainted)
        ]
        assert examined, "coordinator dispatch branch not examined"


# ---------------------------------------------------------------------------
# Checker 1: kernel parity (HS1xx)
# ---------------------------------------------------------------------------


CPP = '''
    extern "C" {
    int hs_foo(const int* a, long long n) {
      return 0;
    }
    }  // extern "C"
'''

NATIVE_OK = '''
    KERNEL_TWINS = {
        "hs_foo": ("foo", "numpy.lexsort"),
    }

    def foo():
        return None
'''

CPP_FUSED = '''
    extern "C" {
    int64_t hs_fused_bar(const int* a, long long n) {
      return 0;
    }
    }  // extern "C"
'''


class TestKernelParity:
    def test_missing_registry_entry(self, tmp_path):
        files = {
            "native/hs_native.cpp": CPP,
            "native/__init__.py": "KERNEL_TWINS = {}\n",
        }
        assert "HS101" in _rules(_lint(tmp_path, files))

    def test_no_registry_at_all(self, tmp_path):
        files = {
            "native/hs_native.cpp": CPP,
            "native/__init__.py": "def foo():\n    return None\n",
        }
        assert "HS101" in _rules(_lint(tmp_path, files))

    def test_stale_entry_and_unresolved_twin(self, tmp_path):
        files = {
            "native/hs_native.cpp": CPP,
            "native/__init__.py": (
                "KERNEL_TWINS = {\n"
                '    "hs_foo": ("missing_wrapper", "pkg.nowhere.fn"),\n'
                '    "hs_gone": ("foo", "numpy.lexsort"),\n'
                "}\n"
                "def foo():\n    return None\n"
            ),
        }
        rules = _rules(_lint(tmp_path, files))
        assert "HS102" in rules and "HS103" in rules

    def test_missing_differential_test(self, tmp_path):
        files = {"native/hs_native.cpp": CPP, "native/__init__.py": NATIVE_OK}
        findings = _lint(
            tmp_path, files, tests={"test_other.py": "def test_x():\n    pass\n"}
        )
        assert "HS104" in _rules(findings)

    def test_clean(self, tmp_path):
        files = {"native/hs_native.cpp": CPP, "native/__init__.py": NATIVE_OK}
        findings = _lint(
            tmp_path,
            files,
            tests={"test_foo.py": "def test_foo():\n    assert foo\n"},
        )
        assert findings == []

    def test_fused_export_with_numpy_twin_flagged(self, tmp_path):
        # seeded violation: a fused-pipeline export registered against a
        # numpy single-op twin — HS105 requires the in-package
        # interpreted chain as the parity reference
        files = {
            "native/hs_native.cpp": CPP_FUSED,
            "native/__init__.py": (
                "KERNEL_TWINS = {\n"
                '    "hs_fused_bar": ("fused_bar", "numpy.lexsort"),\n'
                "}\n"
                "def fused_bar():\n    return None\n"
            ),
        }
        findings = _lint(
            tmp_path,
            files,
            tests={"test_bar.py": "def test_bar():\n    assert fused_bar\n"},
        )
        assert "HS105" in _rules(findings)

    def test_fused_export_with_interpreted_twin_clean(self, tmp_path):
        files = {
            "native/hs_native.cpp": CPP_FUSED,
            "native/__init__.py": (
                "KERNEL_TWINS = {\n"
                '    "hs_fused_bar": ("fused_bar", "pkg.chain.interpreted_bar"),\n'
                "}\n"
                "def fused_bar():\n    return None\n"
            ),
            "chain.py": "def interpreted_bar():\n    return None\n",
        }
        findings = _lint(
            tmp_path,
            files,
            tests={"test_bar.py": "def test_bar():\n    assert fused_bar\n"},
        )
        assert findings == []


# ---------------------------------------------------------------------------
# Checker 2: log state machine (HS2xx)
# ---------------------------------------------------------------------------


CONSTANTS = '''
    class States:
        DOESNOTEXIST = "DOESNOTEXIST"
        CREATING = "CREATING"
        ACTIVE = "ACTIVE"
        DELETING = "DELETING"
        DELETED = "DELETED"

        STABLE_STATES = frozenset({ACTIVE, DELETED, DOESNOTEXIST})

        ROLLBACK = {
            CREATING: DOESNOTEXIST,
            DELETING: ACTIVE,
        }
'''

ACTIONS_CLEAN = '''
    from pkg.constants import States

    class CreateAction:
        transient_state = States.CREATING
        final_state = States.ACTIVE

    class DeleteAction:
        transient_state = States.DELETING
        final_state = States.DELETED
        required_state = States.ACTIVE
'''


class TestLogStateMachine:
    def test_clean(self, tmp_path):
        files = {"constants.py": CONSTANTS, "actions/act.py": ACTIONS_CLEAN}
        assert _lint(tmp_path, files) == []

    def test_illegal_transient_without_rollback(self, tmp_path):
        # seeded illegal transition: ACTIVE used as a transient state —
        # there is no rollback edge, cancel() could never recover it
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": ACTIONS_CLEAN,
            "actions/bad.py": """
                from pkg.constants import States

                class BadAction:
                    transient_state = States.ACTIVE
                    final_state = States.ACTIVE
            """,
        }
        assert "HS201" in _rules(_lint(tmp_path, files))

    def test_commit_to_unstable_state(self, tmp_path):
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": ACTIONS_CLEAN,
            "actions/bad.py": """
                from pkg.constants import States

                class BadAction:
                    transient_state = States.CREATING
                    final_state = States.DELETING
            """,
        }
        assert "HS202" in _rules(_lint(tmp_path, files))

    def test_unknown_state_name(self, tmp_path):
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": ACTIONS_CLEAN
            + "\n    BOGUS = States.FROBNICATING\n",
        }
        assert "HS203" in _rules(_lint(tmp_path, files))

    def test_required_state_mismatch(self, tmp_path):
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": ACTIONS_CLEAN,
            "actions/bad.py": """
                from pkg.constants import States

                class BadAction:
                    transient_state = States.CREATING
                    final_state = States.ACTIVE
                    required_state = States.ACTIVE
            """,
        }
        assert "HS204" in _rules(_lint(tmp_path, files))

    def test_unused_rollback_state(self, tmp_path):
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": """
                from pkg.constants import States

                class CreateAction:
                    transient_state = States.CREATING
                    final_state = States.ACTIVE
            """,
        }
        assert "HS205" in _rules(_lint(tmp_path, files))

    def test_rollback_edge_to_unstable_state(self, tmp_path):
        # seeded broken recovery edge: DELETING rolls back to CREATING
        # (transient) — cancel()/crash recovery would strand differently
        constants = CONSTANTS.replace(
            "DELETING: ACTIVE,", "DELETING: CREATING,"
        )
        files = {"constants.py": constants, "actions/act.py": ACTIONS_CLEAN}
        assert "HS206" in _rules(_lint(tmp_path, files))

    def test_suppression(self, tmp_path):
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": ACTIONS_CLEAN,
            "actions/bad.py": """
                from pkg.constants import States

                class BadAction:
                    transient_state = States.ACTIVE  # hslint: disable=HS201
                    final_state = States.ACTIVE
            """,
        }
        assert _lint(tmp_path, files) == []


# ---------------------------------------------------------------------------
# Checker 3: hot-path purity (HS3xx)
# ---------------------------------------------------------------------------


class TestPurity:
    def test_numpy_in_jit(self, tmp_path):
        files = {
            "ops/k.py": """
                import jax
                import jax.numpy as jnp
                import numpy as np

                @jax.jit
                def bad(x):
                    return np.concatenate([x, x])
            """
        }
        assert "HS301" in _rules(_lint(tmp_path, files))

    def test_host_sync_in_jit(self, tmp_path):
        files = {
            "ops/k.py": """
                import jax

                @jax.jit
                def bad(x):
                    return x.item()
            """
        }
        assert "HS302" in _rules(_lint(tmp_path, files))

    def test_shard_map_by_name_and_partial_jit(self, tmp_path):
        files = {
            "parallel/k.py": """
                import functools
                import jax
                import numpy as np
                from jax.experimental.shard_map import shard_map

                def local(x):
                    return np.argsort(x)

                def run(mesh, x):
                    return shard_map(local, mesh=mesh)(x)

                @functools.partial(jax.jit, static_argnames=("n",))
                def also_bad(x, n):
                    return np.asarray(x)
            """
        }
        findings = _lint(tmp_path, files)
        assert "HS301" in _rules(findings)  # np.argsort in shard_map'd fn
        assert "HS302" in _rules(findings)  # np.asarray under jit

    def test_clean_and_allowlist(self, tmp_path):
        files = {
            "ops/k.py": """
                import jax
                import jax.numpy as jnp
                import numpy as np

                @jax.jit
                def good(x):
                    return jnp.sum(x) + np.uint32(1)

                def host_helper(x):
                    # not traced: host numpy is fine here
                    return np.asarray(x).item()
            """
        }
        assert _lint(tmp_path, files) == []

    def test_suppression(self, tmp_path):
        files = {
            "ops/k.py": """
                import jax
                import numpy as np

                @jax.jit
                def bad(x):
                    # callback runs host-side by contract here
                    return np.log(x)  # hslint: disable=HS301
            """
        }
        assert _lint(tmp_path, files) == []

    def test_suppression_with_inline_justification(self, tmp_path):
        # text after the rule id must not break the suppression match
        files = {
            "ops/k.py": """
                import jax
                import numpy as np

                @jax.jit
                def bad(x):
                    return np.log(x)  # hslint: disable=HS301 host cb contract
            """
        }
        assert _lint(tmp_path, files) == []

    def test_annotations_are_not_traced(self, tmp_path):
        # np.ndarray annotations evaluate at def time, never under trace
        files = {
            "ops/k.py": """
                import jax
                import jax.numpy as jnp
                import numpy as np

                @jax.jit
                def good(x: np.ndarray) -> np.ndarray:
                    y: np.ndarray = jnp.sum(x)
                    return y
            """
        }
        assert _lint(tmp_path, files) == []


# ---------------------------------------------------------------------------
# Checker 4: exception policy (HS4xx)
# ---------------------------------------------------------------------------


class TestExceptPolicy:
    def test_bare_except(self, tmp_path):
        files = {
            "m.py": """
                def f():
                    try:
                        return 1
                    except:
                        return None
            """
        }
        assert "HS401" in _rules(_lint(tmp_path, files))

    def test_broad_except_without_reraise(self, tmp_path):
        files = {
            "m.py": """
                def f():
                    try:
                        return 1
                    except Exception:
                        return None
            """
        }
        assert "HS402" in _rules(_lint(tmp_path, files))

    def test_reraise_is_allowed(self, tmp_path):
        files = {
            "m.py": """
                def f():
                    try:
                        return 1
                    except Exception as e:
                        print(e)
                        raise
            """
        }
        assert _lint(tmp_path, files) == []

    def test_typed_is_clean_and_suppression_works(self, tmp_path):
        files = {
            "m.py": """
                def f():
                    try:
                        return 1
                    except ValueError:
                        return None

                def g():
                    try:
                        return 1
                    # deliberate catch-all: fallback is the contract
                    except Exception:  # hslint: disable=HS402
                        return None
            """
        }
        assert _lint(tmp_path, files) == []


# ---------------------------------------------------------------------------
# Checker 5: locks (HS5xx)
# ---------------------------------------------------------------------------


class TestLocks:
    def test_seeded_lock_order_cycle(self, tmp_path):
        files = {
            "a.py": """
                import threading

                A = threading.Lock()
                B = threading.Lock()

                def f():
                    with A:
                        with B:
                            pass

                def g():
                    with B:
                        with A:
                            pass
            """
        }
        assert "HS501" in _rules(_lint(tmp_path, files))

    def test_cross_function_cycle(self, tmp_path):
        # f holds A and calls helper() which takes B; g does the reverse
        # through its own callee — only the transitive call graph sees it
        files = {
            "a.py": """
                import threading

                A = threading.Lock()
                B = threading.Lock()

                def take_b():
                    with B:
                        pass

                def take_a():
                    with A:
                        pass

                def f():
                    with A:
                        take_b()

                def g():
                    with B:
                        take_a()
            """
        }
        assert "HS501" in _rules(_lint(tmp_path, files))

    def test_lock_held_io_direct_and_via_callee(self, tmp_path):
        files = {
            "a.py": """
                import threading

                A = threading.Lock()

                def io_helper(p):
                    with open(p) as f:
                        return f.read()

                def direct(p):
                    with A:
                        return open(p).read()

                def via_callee(p):
                    with A:
                        return io_helper(p)
            """
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS502"]
        assert len(findings) == 2

    def test_consistent_order_is_clean(self, tmp_path):
        files = {
            "a.py": """
                import threading

                A = threading.Lock()
                B = threading.Lock()

                def f():
                    with A:
                        with B:
                            pass

                def g():
                    with A:
                        with B:
                            pass
            """
        }
        assert _lint(tmp_path, files) == []

    def test_same_class_name_in_two_modules_does_not_alias(self, tmp_path):
        # instance locks are keyed by (module, class): two classes both
        # named Cache must be distinct lock identities, or their edges
        # would merge and could fake a cycle across unrelated modules
        from hyperspace_tpu.analysis.core import Project
        from hyperspace_tpu.analysis.locks import _collect_defs

        src = """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
        """
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        _write_tree(pkg, {"a.py": src, "b.py": src})
        _indexes, locks = _collect_defs(Project(str(pkg)))
        assert len(locks) == 2
        assert {scope for scope, _ in locks} == {
            "cls:a.py:Cache",
            "cls:b.py:Cache",
        }

    def test_instance_locks_and_suppression(self, tmp_path):
        files = {
            "a.py": """
                import threading

                class Cache:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def load(self, p):
                        # one-time load is serialized by design
                        with self._lock:  # hslint: disable=HS502
                            return open(p).read()

                    def get(self, k):
                        with self._lock:
                            return k
            """
        }
        assert _lint(tmp_path, files) == []


# ---------------------------------------------------------------------------
# Checker 6: shared state (HS6xx)
# ---------------------------------------------------------------------------


STATE_OK = """
    import threading

    _lock = threading.Lock()
    cache = {}

    def put(k, v):
        with _lock:
            cache[k] = v

    def read_all():
        with _lock:
            return dict(cache)
"""

SERVE_SUBMIT = """
    from pkg import state

    def worker(item):
        state.put(item, 1)

    def run(pool, items):
        return [pool.submit(worker, i) for i in items]
"""

REGISTRY_OK = '''
    SHARED_STATE = {
        "pkg.state.cache": (
            "pkg.state._lock",
            "guarded",
            "all access under the lock",
        ),
    }
'''


class TestSharedState:
    def test_registered_guarded_is_clean(self, tmp_path):
        files = {
            "concurrency.py": REGISTRY_OK,
            "state.py": STATE_OK,
            "serve.py": SERVE_SUBMIT,
        }
        assert _lint(tmp_path, files) == []

    def test_unregistered_pool_reachable_global(self, tmp_path):
        # seeded violation: a written module global reached from a
        # pool-submitted closure with no SHARED_STATE entry
        files = {
            "concurrency.py": REGISTRY_OK,
            "state.py": STATE_OK,
            "serve.py": SERVE_SUBMIT
            + """
    stats = {}

    def telemetry(item):
        stats[item] = 1

    def run2(pool, items):
        return [pool.submit(telemetry, i) for i in items]
""",
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS601"]
        assert findings and "stats" in findings[0].message

    def test_nested_closure_is_reached(self, tmp_path):
        # the submitted callable is a closure DEFINED INSIDE the
        # submitting function — the resolver must still reach it
        files = {
            "concurrency.py": REGISTRY_OK,
            "state.py": STATE_OK,
            "serve.py": """
    totals = {}

    def run(pool, items):
        def one(i):
            totals[i] = totals.get(i, 0) + 1
        return [pool.submit(one, i) for i in items]
""",
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS601"]
        assert findings and "totals" in findings[0].message

    def test_never_written_global_is_config_not_state(self, tmp_path):
        # a module dict nothing writes (a KERNEL_TWINS-style registry
        # literal) is configuration, not shared state
        files = {
            "concurrency.py": REGISTRY_OK,
            "state.py": STATE_OK,
            "serve.py": SERVE_SUBMIT
            + """
    TABLE = {"a": 1}

    def lookup(item):
        return TABLE.get(item)

    def run3(pool, items):
        return [pool.submit(lookup, i) for i in items]
""",
        }
        assert _lint(tmp_path, files) == []

    def test_guarded_policy_violation(self, tmp_path):
        # seeded violation: a lock-free read of "guarded" state
        files = {
            "concurrency.py": REGISTRY_OK,
            "state.py": STATE_OK
            + """
    def peek(k):
        return cache.get(k)
""",
            "serve.py": SERVE_SUBMIT,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS602"]
        assert findings and "peek" in findings[0].message

    def test_guarded_writes_allows_racy_reads(self, tmp_path):
        registry = REGISTRY_OK.replace('"guarded"', '"guarded-writes"')
        files = {
            "concurrency.py": registry,
            "state.py": STATE_OK
            + """
    def peek(k):
        return cache.get(k)
""",
            "serve.py": SERVE_SUBMIT,
        }
        assert _lint(tmp_path, files) == []

    def test_rebind_only_flags_in_place_mutation(self, tmp_path):
        files = {
            "concurrency.py": '''
    SHARED_STATE = {
        "pkg.state.last_stats": (
            "",
            "rebind-only",
            "published as one atomic rebind",
        ),
    }
''',
            "state.py": """
    last_stats = {}

    def publish_ok(d):
        global last_stats
        last_stats = dict(d)

    def publish_torn(d):
        last_stats.clear()
        last_stats.update(d)
""",
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS602"]
        assert len(findings) == 2  # clear + update; the rebind is clean

    def test_stale_registry_entries(self, tmp_path):
        # three distinct staleness shapes: unknown state path, unknown
        # lock, unknown policy — one HS603 each
        files = {
            "concurrency.py": '''
    SHARED_STATE = {
        "pkg.state.cache": (
            "pkg.state._lock",
            "guarded",
            "all access under the lock",
        ),
        "pkg.state.gone": (
            "pkg.state._lock",
            "guarded",
            "stale",
        ),
        "pkg.state.cache2": (
            "pkg.state._missing_lock",
            "guarded",
            "bad lock",
        ),
        "pkg.state.cache3": (
            "pkg.state._lock",
            "bogus-policy",
            "bad policy",
        ),
    }
''',
            "state.py": STATE_OK + "\n    cache2 = {}\n    cache3 = {}\n",
        }
        rules = [f.rule for f in _lint(tmp_path, files)]
        assert rules.count("HS603") == 3

    def test_missing_justification(self, tmp_path):
        files = {
            "concurrency.py": REGISTRY_OK.replace(
                '"all access under the lock"', '""'
            ),
            "state.py": STATE_OK,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS603"]
        assert findings and "justification" in findings[0].message

    def test_suppression(self, tmp_path):
        files = {
            "concurrency.py": REGISTRY_OK,
            "state.py": STATE_OK,
            "serve.py": SERVE_SUBMIT
            + """
    stats = {}

    def telemetry(item):
        # single-writer bench counter by contract
        stats[item] = 1  # hslint: disable=HS601

    def run2(pool, items):
        return [pool.submit(telemetry, i) for i in items]
""",
        }
        assert _lint(tmp_path, files) == []

    def test_instance_attr_policy(self, tmp_path):
        # registered class attribute: __init__ is exempt, unlocked
        # method access is flagged
        files = {
            "concurrency.py": '''
    SHARED_STATE = {
        "pkg.cachemod.Cache._entries": (
            "self._lock",
            "guarded",
            "map guarded by the instance lock",
        ),
    }
''',
            "cachemod.py": """
    import threading

    class Cache:
        def __init__(self):
            self._lock = threading.Lock()
            self._entries = {}

        def get(self, k):
            with self._lock:
                return self._entries.get(k)

        def size_unlocked(self):
            return len(self._entries)
""",
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS602"]
        assert len(findings) == 1 and "size_unlocked" in findings[0].message


# ---------------------------------------------------------------------------
# Checker 7: contracts (HS7xx)
# ---------------------------------------------------------------------------


CONTRACT_CONSTANTS = """
    FOO = "hyperspace.foo.enabled"
    FOO_DEFAULT = True
    BAR = "hyperspace.bar.limit"
"""

CONTRACT_CONFIG = """
    from pkg import constants as C

    def foo(conf):
        return conf.get_bool(C.FOO, C.FOO_DEFAULT)

    def bar(conf):
        return conf.get_int(C.BAR, 3)
"""

CONTRACT_DOC = """\
# Config

| Key | Default | Meaning |
|---|---|---|
| `hyperspace.foo.enabled` | `true` | the foo switch |
| `hyperspace.bar.limit` | `3` | the bar bound |
"""


def _write_doc(tmp_path, text=CONTRACT_DOC):
    d = tmp_path / "docs"
    d.mkdir(exist_ok=True)
    (d / "CONFIG.md").write_text(text)


class TestContracts:
    def test_missing_default(self, tmp_path):
        _write_doc(tmp_path)
        files = {
            "constants.py": CONTRACT_CONSTANTS,
            "config.py": CONTRACT_CONFIG,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS701"]
        assert len(findings) == 1 and "BAR" in findings[0].message

    def test_literal_key_read(self, tmp_path):
        _write_doc(tmp_path)
        files = {
            "constants.py": CONTRACT_CONSTANTS + "    BAR_DEFAULT = 3\n",
            "config.py": CONTRACT_CONFIG
            + """
    def sneaky(conf):
        return conf.get("hyperspace.sneaky.key")
""",
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS701"]
        assert len(findings) == 1 and "sneaky" in findings[0].message

    def test_undocumented_key(self, tmp_path):
        _write_doc(
            tmp_path,
            CONTRACT_DOC.replace(
                "| `hyperspace.bar.limit` | `3` | the bar bound |\n", ""
            ),
        )
        files = {
            "constants.py": CONTRACT_CONSTANTS + "    BAR_DEFAULT = 3\n",
            "config.py": CONTRACT_CONFIG,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS702"]
        assert len(findings) == 1 and "hyperspace.bar.limit" in findings[0].message

    def test_dead_documented_key(self, tmp_path):
        _write_doc(
            tmp_path,
            CONTRACT_DOC + "| `hyperspace.ghost.key` | `x` | gone |\n",
        )
        files = {
            "constants.py": CONTRACT_CONSTANTS + "    BAR_DEFAULT = 3\n",
            "config.py": CONTRACT_CONFIG,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS704"]
        assert len(findings) == 1 and "ghost" in findings[0].message

    def test_dead_declared_key(self, tmp_path):
        _write_doc(tmp_path)
        files = {
            "constants.py": CONTRACT_CONSTANTS
            + '    BAR_DEFAULT = 3\n    BAZ = "hyperspace.baz.unused"\n',
            "config.py": CONTRACT_CONFIG,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS704"]
        assert len(findings) == 1 and "BAZ" in findings[0].message

    def test_fault_matrix_hole(self, tmp_path):
        _write_doc(tmp_path)
        files = {
            "constants.py": CONTRACT_CONSTANTS + "    BAR_DEFAULT = 3\n",
            "config.py": CONTRACT_CONFIG,
            "testing/faults.py": 'POINTS = ("a_point", "b_point")\n',
        }
        tests = {
            "test_faults.py": "def test_matrix():\n    assert 'a_point'\n"
        }
        findings = [
            f for f in _lint(tmp_path, files, tests=tests) if f.rule == "HS703"
        ]
        assert len(findings) == 1 and "b_point" in findings[0].message

    def test_crash_matrix_hole(self, tmp_path):
        # crash points have their own matrix file: a point missing from
        # tests/test_crash_recovery.py is an untested crash mode
        _write_doc(tmp_path)
        files = {
            "constants.py": CONTRACT_CONSTANTS + "    BAR_DEFAULT = 3\n",
            "config.py": CONTRACT_CONFIG,
            "testing/faults.py": (
                'POINTS = ("a_point",)\n'
                'CRASH_POINTS = ("after_x", "mid_y")\n'
            ),
        }
        tests = {
            "test_faults.py": "def test_matrix():\n    assert 'a_point'\n",
            "test_crash_recovery.py": (
                "def test_crash():\n    assert 'after_x'\n"
            ),
        }
        findings = [
            f for f in _lint(tmp_path, files, tests=tests) if f.rule == "HS703"
        ]
        assert len(findings) == 1 and "mid_y" in findings[0].message
        assert "test_crash_recovery.py" in findings[0].message

    def test_clean_and_prefix_family(self, tmp_path):
        _write_doc(
            tmp_path,
            CONTRACT_DOC
            + "| `hyperspace.faults.<point>` | unset | injection |\n",
        )
        files = {
            "constants.py": CONTRACT_CONSTANTS
            + '    BAR_DEFAULT = 3\n    FAULTS_PREFIX = "hyperspace.faults."\n',
            "config.py": CONTRACT_CONFIG
            + """
    def faults(conf):
        return conf.prefixed(C.FAULTS_PREFIX)
""",
        }
        assert _lint(tmp_path, files) == []

    def test_suppression_in_constants(self, tmp_path):
        _write_doc(tmp_path)
        files = {
            "constants.py": CONTRACT_CONSTANTS.replace(
                'BAR = "hyperspace.bar.limit"',
                '    # required key: no default by design\n'
                '    BAR = "hyperspace.bar.limit"  # hslint: disable=HS701',
            ),
            "config.py": CONTRACT_CONFIG,
        }
        assert _lint(tmp_path, files) == []


# ---------------------------------------------------------------------------
# The lock witness: record → cross-check round trip
# ---------------------------------------------------------------------------


class TestLockWitness:
    @pytest.fixture
    def witness(self):
        # the recorder is process-global: these tests reset and
        # uninstall it, which would gut a session-level recording
        if os.environ.get("HS_LOCK_WITNESS"):
            pytest.skip("HS_LOCK_WITNESS session recording is active")
        from hyperspace_tpu.testing import lock_witness

        lock_witness.reset()
        lock_witness.install()
        try:
            yield lock_witness
        finally:
            lock_witness.uninstall()
            lock_witness.reset()

    def test_round_trip_clean(self, tmp_path, witness):
        # drive real guarded paths: module lock + instance lock
        from hyperspace_tpu.execution.serve_cache import ServeCache
        from hyperspace_tpu.indexes import zonemaps

        cache = ServeCache(1 << 20)
        cache.put(("scan", "fp"), "v", 8)
        assert cache.get(("scan", "fp")) == "v"
        zonemaps.invalidate_local_cache()
        path = str(tmp_path / "witness.json")
        doc = witness.dump(path)
        assert doc["locks"]["execution/serve_cache.py::ServeCache._lock"] >= 2
        assert doc["locks"]["indexes/zonemaps.py::_local_lock"] >= 1
        from hyperspace_tpu.analysis import shared_state as ss
        from hyperspace_tpu.analysis.core import Project

        project = Project(PKG_DIR, tests_dir=TESTS_DIR)
        gaps, _warnings = ss.witness_cross_check(
            [project], ss.load_witness(path), "witness.json"
        )
        assert gaps == []

    def test_model_gap_detected(self, tmp_path, witness):
        # manufacture a nested acquisition the static graph does NOT
        # contain: the cross-check must call it a hard model gap
        from hyperspace_tpu.execution import join_exec
        from hyperspace_tpu.indexes import zonemaps

        with zonemaps._local_lock:
            with join_exec._serve_bd_lock:
                pass
        path = str(tmp_path / "witness.json")
        witness.dump(path)
        from hyperspace_tpu.analysis import shared_state as ss
        from hyperspace_tpu.analysis.core import Project

        project = Project(PKG_DIR, tests_dir=TESTS_DIR)
        gaps, _warnings = ss.witness_cross_check(
            [project], ss.load_witness(path), "witness.json"
        )
        assert len(gaps) == 1 and gaps[0].rule == "HS604"
        assert "_local_lock" in gaps[0].message
        assert "_serve_bd_lock" in gaps[0].message

    def test_artifacts_merge(self, tmp_path, witness):
        from hyperspace_tpu.indexes import zonemaps

        path = str(tmp_path / "witness.json")
        zonemaps.invalidate_local_cache()
        first = witness.dump(path)
        witness.reset()
        zonemaps.invalidate_local_cache()
        second = witness.dump(path)
        key = "indexes/zonemaps.py::_local_lock"
        assert second["locks"][key] == first["locks"][key] + 1

    def test_malformed_artifact_rejected(self, tmp_path):
        # every malformed shape must raise ValueError (the CLI's exit-2
        # contract), never crash downstream with a raw traceback
        from hyperspace_tpu.analysis import shared_state as ss

        bad_docs = [
            '{"not": "a witness"}',
            '{"version": 1, "locks": {}, "edges": [["one_element"]]}',
            '{"version": 1, "locks": ["a"], "edges": []}',
            '{"version": 1, "locks": {"a": "n"}, "edges": []}',
        ]
        for i, text in enumerate(bad_docs):
            p = tmp_path / f"bad{i}.json"
            p.write_text(text)
            with pytest.raises(ValueError):
                ss.load_witness(str(p))


# ---------------------------------------------------------------------------
# Checker 8: SPMD collective symmetry (HS8xx)
# ---------------------------------------------------------------------------


SPMD_REGISTRY = '''
    COLLECTIVE_SITES = {
        "pkg.comm.exchange": (
            "all_to_all",
            "symmetric-all",
            "every process exchanges at the same step",
        ),
    }
'''

SPMD_COMM = """
    from jax import lax

    def exchange(x):
        return lax.all_to_all(x, "s", 0, 0)
"""

SPMD_GATED_REGISTRY = '''
    COLLECTIVE_SITES = {
        "pkg.comm.exchange": (
            "all_to_all",
            "symmetric-all",
            "every process exchanges at the same step",
        ),
        "pkg.logplane.publish": (
            "log_write",
            "coordinator-gated",
            "single-writer metadata seam",
        ),
    }
'''


class TestSpmd:
    def test_identity_branch_skipping_collective(self, tmp_path):
        # seeded violation: process 0 exchanges, everyone else returns —
        # the PR 11 bug shape, statically
        files = {
            "collectives.py": SPMD_REGISTRY,
            "comm.py": SPMD_COMM,
            "driver.py": """
                import jax

                from pkg.comm import exchange

                def run(x):
                    if jax.process_index() == 0:
                        return exchange(x)
                    return x
            """,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS801"]
        assert findings and "exchange" in findings[0].message

    def test_identity_branch_via_tainted_local(self, tmp_path):
        # the identity value rides a local name; the taint must follow
        files = {
            "collectives.py": SPMD_REGISTRY,
            "comm.py": SPMD_COMM,
            "driver.py": """
                import jax

                from pkg.comm import exchange

                def run(x):
                    pid = jax.process_index()
                    if pid == 0:
                        exchange(x)
                    return x
            """,
        }
        assert "HS801" in _rules(_lint(tmp_path, files))

    def test_symmetric_branch_is_clean(self, tmp_path):
        # both paths reach the collective (the branch only picks the
        # payload): no divergence
        files = {
            "collectives.py": SPMD_REGISTRY,
            "comm.py": SPMD_COMM,
            "driver.py": """
                import jax

                from pkg.comm import exchange

                def run(x, y):
                    if jax.process_index() == 0:
                        out = exchange(x)
                    else:
                        out = exchange(y)
                    return out
            """,
        }
        assert _lint(tmp_path, files) == []

    def test_process_count_branch_is_uniform(self, tmp_path):
        # every process agrees on process_count(): gating a collective
        # on it cannot diverge and must stay clean (the single-vs-multi
        # guard idiom all over covering_build)
        files = {
            "collectives.py": SPMD_REGISTRY,
            "comm.py": SPMD_COMM,
            "driver.py": """
                import jax

                from pkg.comm import exchange

                def run(x):
                    if jax.process_count() > 1:
                        return exchange(x)
                    return x
            """,
        }
        assert _lint(tmp_path, files) == []

    def test_coordinator_gated_branch_is_clean(self, tmp_path):
        # gating a coordinator-gated site on is_coordinator IS the
        # contract; the symmetric collective after the branch is reached
        # by both paths
        files = {
            "collectives.py": SPMD_GATED_REGISTRY,
            "comm.py": SPMD_COMM,
            "logplane.py": """
                from jax.experimental import multihost_utils as mhu

                def publish(x):
                    return mhu.broadcast_one_to_all(x)
            """,
            "driver.py": """
                from pkg.comm import exchange
                from pkg.logplane import publish

                def run(mesh, x):
                    if mesh.is_coordinator:
                        publish(x)
                    return exchange(x)
            """,
        }
        assert _lint(tmp_path, files) == []

    def test_unregistered_collective(self, tmp_path):
        # seeded violation: a ppermute with no COLLECTIVE_SITES entry
        files = {
            "collectives.py": SPMD_REGISTRY,
            "comm.py": SPMD_COMM,
            "rogue.py": """
                from jax import lax

                def sneak(x):
                    return lax.ppermute(x, "s", [(0, 1)])
            """,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS802"]
        assert findings and "sneak" in findings[0].message

    def test_stale_registry_entries(self, tmp_path):
        # four staleness shapes: unresolved path, unknown contract,
        # missing justification, non-gated entry with no collective
        files = {
            "collectives.py": '''
    COLLECTIVE_SITES = {
        "pkg.comm.exchange": (
            "all_to_all",
            "symmetric-all",
            "every process exchanges at the same step",
        ),
        "pkg.comm.gone": ("all_to_all", "symmetric-all", "stale"),
        "pkg.comm.exchange2": ("all_to_all", "bogus-contract", "bad"),
        "pkg.comm.exchange3": ("all_to_all", "symmetric-all", ""),
        "pkg.comm.quiet": ("all_to_all", "symmetric-all", "no op inside"),
    }
''',
            "comm.py": SPMD_COMM
            + """
    def exchange2(x):
        return lax.all_to_all(x, "s", 0, 0)

    def exchange3(x):
        return lax.all_to_all(x, "s", 0, 0)

    def quiet(x):
        return x
""",
        }
        rules = [f.rule for f in _lint(tmp_path, files)]
        assert rules.count("HS802") == 4

    def test_process_local_loop_bound(self, tmp_path):
        # seeded violation: the wave-count bug — a collective inside a
        # loop over this process's file stripe
        files = {
            "collectives.py": SPMD_REGISTRY,
            "comm.py": SPMD_COMM,
            "driver.py": """
                import jax

                from pkg.comm import exchange

                def waves(files, x):
                    mine = files[jax.process_index()::jax.process_count()]
                    for f in mine:
                        x = exchange(x)
                    return x
            """,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS803"]
        assert findings and "exchange" in findings[0].message

    def test_allgathered_loop_bound_is_clean(self, tmp_path):
        # process_allgather sanitizes: the bound is global by contract
        files = {
            "collectives.py": SPMD_REGISTRY,
            "comm.py": SPMD_COMM,
            "driver.py": """
                import jax
                from jax.experimental import multihost_utils as mhu

                from pkg.comm import exchange

                def waves(local_counts, x):
                    counts = mhu.process_allgather(local_counts)
                    for c in counts:
                        x = exchange(x)
                    return x
            """,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS803"]
        assert findings == []

    def test_suppression(self, tmp_path):
        files = {
            "collectives.py": SPMD_REGISTRY,
            "comm.py": SPMD_COMM,
            "driver.py": """
                import jax

                from pkg.comm import exchange

                def run(x):
                    # single-process probe path by contract
                    if jax.process_index() == 0:  # hslint: disable=HS801
                        return exchange(x)
                    return x
            """,
        }
        assert _lint(tmp_path, files) == []


# ---------------------------------------------------------------------------
# The collective witness: record → merge → cross-check round trip
# ---------------------------------------------------------------------------


def _spmd_project(tmp_path, registry=SPMD_REGISTRY):
    from hyperspace_tpu.analysis.core import Project

    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    _write_tree(pkg, {"collectives.py": registry, "comm.py": SPMD_COMM})
    return Project(str(pkg))


def _cw_artifact(tmp_path, process, sequence, prefix="cw"):
    import json

    doc = {
        "version": 1,
        "package": "pkg",
        "process": process,
        "process_count": 2,
        "registered": {},
        "sequence": sequence,
    }
    p = tmp_path / f"{prefix}.p{process}.json"
    p.write_text(json.dumps(doc))
    return str(tmp_path / prefix)


def _rec(site, wave=0, op="all_to_all", sig="(int32[1d])", contract="symmetric-all"):
    return {"site": site, "op": op, "wave": wave, "sig": sig, "contract": contract}


class TestCollectiveWitness:
    SITE = "pkg.comm.exchange"

    def test_round_trip_clean(self, tmp_path):
        from hyperspace_tpu.analysis import spmd

        project = _spmd_project(tmp_path)
        seq = [_rec(self.SITE, 0), _rec(self.SITE, 1)]
        _cw_artifact(tmp_path, 0, seq)
        prefix = _cw_artifact(tmp_path, 1, seq)
        docs = spmd.load_collective_witness(prefix)
        assert [d["process"] for d in docs] == [0, 1]
        findings, warnings = spmd.collective_cross_check([project], docs, "cw")
        assert findings == []
        assert warnings == []  # the one registered site was witnessed

    def test_desynchronized_sequences(self, tmp_path):
        # process 1 skipped the second exchange: hard divergence
        from hyperspace_tpu.analysis import spmd

        project = _spmd_project(tmp_path)
        _cw_artifact(tmp_path, 0, [_rec(self.SITE, 0), _rec(self.SITE, 1)])
        prefix = _cw_artifact(tmp_path, 1, [_rec(self.SITE, 0)])
        docs = spmd.load_collective_witness(prefix)
        findings, _w = spmd.collective_cross_check([project], docs, "cw")
        assert len(findings) == 1 and findings[0].rule == "HS804"
        assert "divergence" in findings[0].message

    def test_signature_divergence_on_symmetric_site(self, tmp_path):
        from hyperspace_tpu.analysis import spmd

        project = _spmd_project(tmp_path)
        _cw_artifact(tmp_path, 0, [_rec(self.SITE, sig="(int32[1d])")])
        prefix = _cw_artifact(tmp_path, 1, [_rec(self.SITE, sig="(int64[1d])")])
        docs = spmd.load_collective_witness(prefix)
        findings, _w = spmd.collective_cross_check([project], docs, "cw")
        assert len(findings) == 1 and "signatures differ" in findings[0].message

    def test_witnessed_unregistered_site(self, tmp_path):
        from hyperspace_tpu.analysis import spmd

        project = _spmd_project(tmp_path)
        seq = [_rec(self.SITE, 0), _rec("pkg.rogue.sneak", 0, op="ppermute")]
        _cw_artifact(tmp_path, 0, seq)
        prefix = _cw_artifact(tmp_path, 1, seq)
        docs = spmd.load_collective_witness(prefix)
        findings, _w = spmd.collective_cross_check([project], docs, "cw")
        assert len(findings) == 1 and findings[0].rule == "HS804"
        assert "pkg.rogue.sneak" in findings[0].message

    def test_coordinator_gated_on_worker(self, tmp_path):
        from hyperspace_tpu.analysis import spmd

        project = _spmd_project(tmp_path, registry=SPMD_GATED_REGISTRY)
        pkg = tmp_path / "pkg"
        _write_tree(
            pkg,
            {
                "logplane.py": """
    from jax.experimental import multihost_utils as mhu

    def publish(x):
        return mhu.broadcast_one_to_all(x)
"""
            },
        )
        from hyperspace_tpu.analysis.core import Project

        project = Project(str(pkg))
        gated = _rec(
            "pkg.logplane.publish",
            op="log_write",
            contract="coordinator-gated",
        )
        _cw_artifact(tmp_path, 0, [_rec(self.SITE), gated])
        prefix = _cw_artifact(tmp_path, 1, [_rec(self.SITE), gated])
        docs = spmd.load_collective_witness(prefix)
        findings, _w = spmd.collective_cross_check([project], docs, "cw")
        # gated on process 1 is the single hard error; the gated records
        # are FILTERED from the sequence comparison (no false divergence)
        assert len(findings) == 1 and findings[0].rule == "HS804"
        assert "coordinator-gated" in findings[0].message

    def test_never_witnessed_is_warning(self, tmp_path):
        from hyperspace_tpu.analysis import spmd

        project = _spmd_project(tmp_path)
        _cw_artifact(tmp_path, 0, [])
        prefix = _cw_artifact(tmp_path, 1, [])
        docs = spmd.load_collective_witness(prefix)
        findings, warnings = spmd.collective_cross_check([project], docs, "cw")
        assert findings == []
        assert warnings and "never witnessed" in warnings[0]

    def test_malformed_artifacts_rejected(self, tmp_path):
        import json

        from hyperspace_tpu.analysis import spmd

        bad_docs = [
            '{"not": "a witness"}',
            '{"process": "zero", "sequence": []}',
            '{"process": 0, "sequence": [{"site": 1}]}',
            '{"process": 0, "sequence": [], "registered": []}',
        ]
        for i, text in enumerate(bad_docs):
            p = tmp_path / f"bad{i}.json"
            p.write_text(text)
            with pytest.raises(ValueError):
                spmd.load_collective_witness(str(p))
        with pytest.raises(ValueError):
            spmd.load_collective_witness(str(tmp_path / "absent_prefix"))
        # duplicate process indexes across a family are torn recordings
        doc = {"process": 0, "sequence": [], "registered": {}}
        (tmp_path / "dup.p0.json").write_text(json.dumps(doc))
        (tmp_path / "dup.p00.json").write_text(json.dumps(doc))
        with pytest.raises(ValueError):
            spmd.load_collective_witness(str(tmp_path / "dup"))

    def test_runtime_record_and_dump(self, tmp_path):
        # the real recorder against the real registry: wrap, drive one
        # registered site single-process, dump, reload, cross-check
        from hyperspace_tpu.analysis import spmd
        from hyperspace_tpu.analysis.core import Project
        from hyperspace_tpu.testing import collective_witness as cw

        cw.reset()
        wrapped = cw.install()
        try:
            assert (
                wrapped["hyperspace_tpu.actions.base._publish_log"]
                == "coordinator-gated"
            )
            from hyperspace_tpu.indexes import covering_build

            # single-process _global_written returns early but the call
            # itself is recorded — in-module callers resolve the name
            # through module globals, so the wrapper is seen
            out = covering_build._global_written(None, ["a.parquet"])
            assert out == ["a.parquet"]
            prefix = str(tmp_path / "cw")
            doc = cw.dump(prefix)
        finally:
            cw.uninstall()
            cw.reset()
        assert doc["process"] == 0
        sites = [r["site"] for r in doc["sequence"]]
        assert sites == [
            "hyperspace_tpu.indexes.covering_build._global_written"
        ]
        assert doc["sequence"][0]["wave"] == 0
        docs = spmd.load_collective_witness(prefix)
        findings, _w = spmd.collective_cross_check(
            [Project(PKG_DIR, tests_dir=TESTS_DIR)], docs, "cw"
        )
        assert findings == []

    def test_contracts_require_dryrun_coverage(self, tmp_path):
        # the HS703 extension: a registered collective site absent from
        # scripts/dryrun_multihost.py is a witness-matrix hole
        scripts = tmp_path / "scripts"
        scripts.mkdir()
        (scripts / "dryrun_multihost.py").write_text(
            'WITNESS = ("pkg.comm.exchange",)\n'
        )
        files = {
            "collectives.py": SPMD_GATED_REGISTRY,
            "comm.py": SPMD_COMM,
            "logplane.py": """
    from jax.experimental import multihost_utils as mhu

    def publish(x):
        return mhu.broadcast_one_to_all(x)
""",
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS703"]
        assert len(findings) == 1
        assert "pkg.logplane.publish" in findings[0].message
        # trailing-name (prefix-family) match: naming just the callable
        # in a WITNESS_* tuple satisfies the rule
        (scripts / "dryrun_multihost.py").write_text(
            'WITNESS = ("pkg.comm.exchange", "publish")\n'
        )
        assert [f for f in _lint(tmp_path, files) if f.rule == "HS703"] == []


# ---------------------------------------------------------------------------
# HS9xx — observability-site lints (analysis/obs.py)
# ---------------------------------------------------------------------------

OBS_REGISTRY = """
    KINDS = ("span", "metric", "view")
    SERVE_STAGES = ("scan", "prepare")
    BUILD_STAGES = ("write",)
    ROOT_NAMES = ("serve.query",)
    OBS_SITES = {
        "pkg.app.serve": ("span", "roots the query at admission"),
    }
"""

OBS_APP = """
    from pkg.obs import trace

    def serve():
        r = trace.root("serve.query")
        trace.stage("scan", 0.0)
        return r
"""


class TestObsSites:
    def test_clean_tree(self, tmp_path):
        findings = _lint(
            tmp_path, {"sites.py": OBS_REGISTRY, "app.py": OBS_APP}
        )
        assert [f for f in findings if f.rule.startswith("HS9")] == []

    def test_no_registry_skips_checker(self, tmp_path):
        # trees without an OBS_SITES registry have no obs plane to lint
        findings = _lint(tmp_path, {"app.py": OBS_APP})
        assert [f for f in findings if f.rule.startswith("HS9")] == []

    def test_undeclared_site_flagged(self, tmp_path):
        files = {
            "sites.py": OBS_REGISTRY,
            "app.py": OBS_APP,
            "rogue.py": """
                from pkg.obs import trace

                def hot_loop():
                    with trace.span("scan"):
                        return 1
            """,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS901"]
        assert len(findings) == 1
        assert "pkg.rogue.hot_loop" in findings[0].message

    def test_nested_def_attributes_to_outermost(self, tmp_path):
        files = {
            "sites.py": OBS_REGISTRY,
            "rogue.py": """
                from pkg.obs import trace

                def outer():
                    def inner():
                        trace.stage("scan", 0.0)
                    return inner
            """,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS901"]
        assert len(findings) == 1
        assert "pkg.rogue.outer" in findings[0].message

    def test_module_level_metric_site(self, tmp_path):
        files = {
            "sites.py": OBS_REGISTRY.replace(
                '"pkg.app.serve": ("span", "roots the query at admission"),',
                '"pkg.app.serve": ("span", "roots the query at admission"),\n'
                '        "pkg.instruments": ("metric", "module-level '
                'registration"),',
            ),
            "app.py": OBS_APP,
            "instruments.py": """
                from pkg.obs import metrics

                registry = metrics.registry
                c = registry.counter("hs_x_total", "x")
            """,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS901"]
        assert findings == []

    def test_suppression_silences(self, tmp_path):
        files = {
            "sites.py": OBS_REGISTRY,
            "rogue.py": """
                from pkg.obs import trace

                def hot_loop():
                    # justified one-off probe
                    trace.stage("scan", 0.0)  # hslint: disable=HS901
            """,
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS901"]
        assert findings == []

    def test_stage_name_outside_vocabulary(self, tmp_path):
        files = {
            "sites.py": OBS_REGISTRY,
            "app.py": OBS_APP.replace('trace.stage("scan", 0.0)',
                                      'trace.stage("scanx", 0.0)'),
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS902"]
        assert len(findings) == 1
        assert "'scanx'" in findings[0].message

    def test_root_name_outside_vocabulary(self, tmp_path):
        files = {
            "sites.py": OBS_REGISTRY,
            "app.py": OBS_APP.replace('trace.root("serve.query")',
                                      'trace.root("mystery")'),
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS902"]
        assert len(findings) == 1
        assert "'mystery'" in findings[0].message

    def test_stale_entries_flagged(self, tmp_path):
        stale_registry = """
            KINDS = ("span", "metric", "view")
            SERVE_STAGES = ("scan",)
            ROOT_NAMES = ("serve.query",)
            OBS_SITES = {
                "pkg.app.serve": ("span", "roots the query"),
                "pkg.gone.fn": ("span", "site no longer exists"),
                "pkg.app.serve_other": ("wat", "unknown kind"),
                "pkg.app.quiet": ("span", "declared but never calls"),
                "pkg.app.nowhy": ("span", ""),
            }
        """
        files = {
            "sites.py": stale_registry,
            "app.py": OBS_APP + """
    def serve_other():
        return 1

    def quiet():
        return 2

    def nowhy():
        return 3
""",
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS903"]
        msgs = "\n".join(f.message for f in findings)
        assert "pkg.gone.fn" in msgs and "does not resolve" in msgs
        assert "unknown kind" in msgs
        assert "no obs primitive call" in msgs
        assert "no justification" in msgs
        assert len(findings) == 4

    def test_real_registry_resolves_and_engages(self):
        """Engagement guard over the real tree: the registry parses,
        every entry resolves and is exercised, and the serve/build
        taxonomies cover the breakdown keys the spans mirror."""
        from hyperspace_tpu.analysis import obs as obs_checker
        from hyperspace_tpu.analysis.core import Project
        from hyperspace_tpu.obs import sites as obs_sites

        project = Project(PKG_DIR, tests_dir=TESTS_DIR)
        entries, stages, roots, rel = obs_checker.parse_sites(project)
        assert rel == "obs/sites.py"
        assert len(entries) >= 10
        assert stages == set(obs_sites.STAGE_NAMES)
        assert "serve.query" in roots
        resolvable = obs_checker._resolvable_paths(project)
        for e in entries:
            assert e.path in resolvable, e.path
        calls = obs_checker._scan_calls(project)
        called = {c.site for c in calls}
        # every declared site calls a primitive; every primitive call
        # site is declared (the package-clean gate enforces the same,
        # this asserts the checker actually SEES them)
        assert {e.path for e in entries} <= called
        # the serve breakdown keys all have span vocabulary entries
        for key in ("scan", "prepare", "match", "expand", "verify",
                    "assemble", "delta"):
            assert key in obs_sites.SERVE_STAGES, key
        for key in ("scan", "hash_shuffle", "sort", "write"):
            assert key in obs_sites.BUILD_STAGES, key


# ---------------------------------------------------------------------------
# HS10xx: memory-residency contract (analysis/residency.py)
# ---------------------------------------------------------------------------

RES_REGISTRY = """
    PLANES = ("build", "serve", "maintenance")
    BOUND_CLASSES = (
        "cache-governed",
        "wave-budget",
        "chunk-bounded",
        "row-group-bounded",
        "const-bounded",
    )
    ALLOC_SITES = {
        "pkg.io.reader.load_table": (
            "serve",
            "cache-governed",
            "materialized table is charged into the serve cache",
        ),
        "pkg.execution.scan.stream_chunks": (
            "build",
            "chunk-bounded",
            "reads the file list in fixed-size chunks",
        ),
    }
"""

RES_IO = """
    def read_table(paths):
        return paths

    def load_table(cache, paths):
        t = read_table(paths)
        cache.put("t", t)
        return t
"""

RES_EXEC = """
    from pkg.io.reader import read_table

    def stream_chunks(files):
        out = []
        for start in range(0, len(files), 8):
            out.append(read_table(files[start : start + 8]))
        return out
"""

RES_FILES = {
    "memory.py": RES_REGISTRY,
    "io/reader.py": RES_IO,
    "execution/scan.py": RES_EXEC,
}


def _res(findings):
    return [f for f in findings if f.rule.startswith("HS10")]


class TestResidency:
    def test_clean_tree(self, tmp_path):
        assert _res(_lint(tmp_path, RES_FILES)) == []

    def test_no_registry_skips_checker(self, tmp_path):
        # trees without an ALLOC_SITES registry have no residency
        # contract to lint — even with unbounded hot-path reads
        files = {
            "io/reader.py": RES_IO,
            "io/rogue.py": """
                def hot_read(paths):
                    return read_table(paths)
            """,
        }
        assert _res(_lint(tmp_path, files)) == []

    def test_undeclared_materialization_flagged(self, tmp_path):
        files = dict(RES_FILES)
        files["io/rogue.py"] = """
            def hot_read(paths):
                return read_table(paths)
        """
        findings = [
            f for f in _lint(tmp_path, files) if f.rule == "HS1001"
        ]
        assert len(findings) == 1
        assert "pkg.io.rogue.hot_read" in findings[0].message
        assert "read_table" in findings[0].message

    def test_arrow_materializer_on_tainted_value(self, tmp_path):
        # the read AND the decode of its (relation-sized) result are
        # both row-proportional materializations
        files = dict(RES_FILES)
        files["io/wide.py"] = """
            def widen(files):
                t = read_table(files)
                return t.to_numpy()
        """
        findings = [
            f for f in _lint(tmp_path, files) if f.rule == "HS1001"
        ]
        assert len(findings) == 2
        msgs = "\n".join(f.message for f in findings)
        assert "to_numpy" in msgs

    def test_unbounded_accumulation_flagged(self, tmp_path):
        # an accumulator appended to once per file of the relation is
        # itself relation-proportional; concatenating it materializes
        files = dict(RES_FILES)
        files["execution/gather.py"] = """
            import numpy as np

            def gather(files):
                parts = []
                for f in files:
                    parts.append(decode(f))
                return np.concatenate(parts)
        """
        findings = [
            f for f in _lint(tmp_path, files) if f.rule == "HS1001"
        ]
        assert len(findings) == 1
        assert "concatenate" in findings[0].message

    def test_slice_read_not_flagged(self, tmp_path):
        # the row-group read path is bounded by construction
        files = dict(RES_FILES)
        files["io/rg.py"] = """
            def per_group(paths, sel):
                return read_table_row_groups(paths, sel)
        """
        assert [
            f for f in _lint(tmp_path, files) if f.rule == "HS1001"
        ] == []

    def test_private_helper_outside_closure_not_flagged(self, tmp_path):
        # HS1001 audits the reach closure from the public surface;
        # an uncalled private helper is not on the hot path
        files = dict(RES_FILES)
        files["io/cold.py"] = """
            def _cold(paths):
                return read_table(paths)
        """
        assert [
            f for f in _lint(tmp_path, files) if f.rule == "HS1001"
        ] == []

    def test_cold_dir_not_flagged(self, tmp_path):
        # only execution/ indexes/ io/ serve/ are the hot path
        files = dict(RES_FILES)
        files["tooling.py"] = """
            def offline_read(paths):
                return read_table(paths)
        """
        assert [
            f for f in _lint(tmp_path, files) if f.rule == "HS1001"
        ] == []

    def test_suppression_silences(self, tmp_path):
        files = dict(RES_FILES)
        files["io/rogue.py"] = """
            def hot_read(paths):
                # justified: caller holds one row group at a time
                return read_table(paths)  # hslint: disable=HS1001
        """
        assert [
            f for f in _lint(tmp_path, files) if f.rule == "HS1001"
        ] == []

    def test_cache_governed_without_put_flagged(self, tmp_path):
        files = dict(RES_FILES)
        files["io/reader.py"] = RES_IO.replace('cache.put("t", t)', "pass")
        findings = [
            f for f in _lint(tmp_path, files) if f.rule == "HS1002"
        ]
        assert len(findings) == 1
        assert "pkg.io.reader.load_table" in findings[0].message
        assert "never flows through" in findings[0].message

    def test_chunk_bounded_without_loop_flagged(self, tmp_path):
        files = dict(RES_FILES)
        files["execution/scan.py"] = """
            from pkg.io.reader import read_table

            def stream_chunks(files):
                return read_table(files)
        """
        findings = [
            f for f in _lint(tmp_path, files) if f.rule == "HS1002"
        ]
        assert len(findings) == 1
        assert "no chunk loop" in findings[0].message

    def test_stale_entries_flagged(self, tmp_path):
        stale_registry = """
            ALLOC_SITES = {
                "pkg.io.reader.load_table": (
                    "serve", "cache-governed", "cached"
                ),
                "pkg.gone.fn": (
                    "serve", "cache-governed", "site no longer exists"
                ),
                "pkg.io.reader.read_table": (
                    "orbit", "cache-governed", "unknown plane"
                ),
                "pkg.io.reader.badbound": (
                    "serve", "mystery", "unknown bound class"
                ),
                "pkg.io.reader.nowhy": ("serve", "const-bounded", ""),
                "pkg.io.reader.quiet": (
                    "serve", "const-bounded", "never allocates"
                ),
            }
        """
        files = {
            "memory.py": stale_registry,
            "io/reader.py": RES_IO + """
    def badbound():
        return 1

    def nowhy():
        return 2

    def quiet():
        return 3
""",
        }
        findings = [
            f for f in _lint(tmp_path, files) if f.rule == "HS1003"
        ]
        msgs = "\n".join(f.message for f in findings)
        assert "pkg.gone.fn" in msgs and "does not resolve" in msgs
        assert "unknown plane" in msgs
        assert "unknown bound" in msgs
        assert "no justification" in msgs
        assert "neither allocates" in msgs
        assert len(findings) == 5

    def test_witness_cross_check_unit(self, tmp_path):
        """Model gaps and ceiling breaches from a crafted artifact
        against a fixture registry — the `hslint --witness` core."""
        from hyperspace_tpu.analysis import residency
        from hyperspace_tpu.analysis.core import Project

        pkg = tmp_path / "pkg"
        pkg.mkdir()
        _write_tree(pkg, RES_FILES)
        project = Project(str(pkg))
        doc = {
            "version": 1,
            "sites": {
                "pkg.io.reader.load_table": {
                    "peak_bytes": 150,
                    "calls": 2,
                },
                "ghost.mod.fn": {"peak_bytes": 7, "calls": 1},
            },
            "budgets": {"cache-governed": 100, "chunk-bounded": 50},
        }
        gaps, warnings = residency.witness_cross_check(
            [project], doc, "res.json"
        )
        assert sorted(f.rule for f in gaps) == ["HS1004", "HS1004"]
        msgs = "\n".join(f.message for f in gaps)
        assert "ghost.mod.fn" in msgs and "absent from ALLOC_SITES" in msgs
        assert "ceiling" in msgs and "150" in msgs
        # the never-driven registered site warns, never errors
        assert any("stream_chunks" in w for w in warnings)
        # malformed artifacts raise (the CLI maps this to exit 2)
        with pytest.raises(ValueError):
            residency.load_witness("x.json", doc={"sites": {"a": 3}})
        with pytest.raises(ValueError):
            residency.load_witness("x.json", doc={"version": 1})

    def test_witness_round_trip(self, tmp_path):
        """install → drive a registered site → dump → merge → static
        cross-check: the full runtime loop over the REAL registry."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from hyperspace_tpu.analysis import residency
        from hyperspace_tpu.analysis.core import Project
        from hyperspace_tpu.testing import residency_witness

        f = tmp_path / "t.parquet"
        pq.write_table(
            pa.table({"a": pa.array(range(1000), type=pa.int64())}),
            str(f),
        )
        art = str(tmp_path / "res.json")
        site = "hyperspace_tpu.io.parquet.read_table"
        residency_witness.reset()
        wrapped = residency_witness.install()
        try:
            from hyperspace_tpu.io import parquet as hp

            hp.read_table([str(f)])
            residency_witness.dump(art)
            residency_witness.reset()
            hp.read_table([str(f)])
            doc = residency_witness.dump(art)  # merges with the first
        finally:
            residency_witness.uninstall()
            residency_witness.reset()
        # every registered site resolves to something wrappable
        assert all(wrapped.values()), [
            s for s, ok in wrapped.items() if not ok
        ]
        rec = doc["sites"][site]
        assert rec["calls"] == 2  # merge sums calls across dumps
        assert rec["peak_bytes"] >= 1000 * 8  # the int64 column
        assert doc["rss_high_water"] > 0
        # budgets are stamped from memory.BOUND_CLASS_CEILINGS
        from hyperspace_tpu import memory

        assert doc["budgets"] == memory.BOUND_CLASS_CEILINGS
        # the artifact round-trips through the static cross-check clean
        loaded = residency.load_witness(art)
        project = Project(PKG_DIR, tests_dir=TESTS_DIR)
        gaps, warnings = residency.witness_cross_check(
            [project], loaded, "res.json"
        )
        assert gaps == []
        assert warnings  # sites this run never drove warn as stale

    def test_real_registry_resolves_and_engages(self):
        """Engagement guard over the real tree: the registry parses,
        every entry resolves to an indexed function/method with a live
        allocation, and the declared taxonomy covers all five bound
        classes the witness gates on."""
        from hyperspace_tpu import memory
        from hyperspace_tpu.analysis import residency
        from hyperspace_tpu.analysis.core import Project

        project = Project(PKG_DIR, tests_dir=TESTS_DIR)
        entries, rel = residency.parse_sites(project)
        assert rel == "memory.py"
        assert len(entries) >= 20
        # the parsed (never-imported) registry matches the runtime one
        assert {e.path for e in entries} == set(memory.ALLOC_SITES)
        for e in entries:
            assert e.plane in residency.PLANES, e.path
            assert e.bound in residency.BOUND_CLASSES, e.path
            assert e.why.strip(), e.path
        index = residency.build_index(project)
        by_site = {fn.site for fn in index.values()}
        for e in entries:
            assert e.path in by_site, e.path
        # declared sites are actually on the audited hot path
        closure_sites = {
            index[k].site for k in residency.reach_closure(index)
        }
        assert "hyperspace_tpu.io.parquet.read_table" in closure_sites
        assert (
            "hyperspace_tpu.execution.join_exec.prepare_join_side"
            in closure_sites
        )
        # every bound class is exercised by some declared site, and
        # every class has a witness ceiling
        assert {e.bound for e in entries} == set(residency.BOUND_CLASSES)
        assert set(memory.BOUND_CLASS_CEILINGS) == set(
            residency.BOUND_CLASSES
        )
        assert residency.PLANES == memory.PLANES
        assert residency.BOUND_CLASSES == memory.BOUND_CLASSES


# ---------------------------------------------------------------------------
# Golden: ruleset + finding schema stability
# ---------------------------------------------------------------------------


class TestGolden:
    EXPECTED_RULES = [
        "HS001",
        "HS1001",
        "HS1002",
        "HS1003",
        "HS1004",
        "HS101",
        "HS102",
        "HS103",
        "HS104",
        "HS105",
        "HS201",
        "HS202",
        "HS203",
        "HS204",
        "HS205",
        "HS206",
        "HS301",
        "HS302",
        "HS401",
        "HS402",
        "HS501",
        "HS502",
        "HS601",
        "HS602",
        "HS603",
        "HS604",
        "HS701",
        "HS702",
        "HS703",
        "HS704",
        "HS801",
        "HS802",
        "HS803",
        "HS804",
        "HS901",
        "HS902",
        "HS903",
    ]

    def test_ruleset_is_stable(self):
        assert sorted(ALL_RULES) == self.EXPECTED_RULES
        for rule, desc in ALL_RULES.items():
            assert desc and isinstance(desc, str)

    def test_every_checker_owns_rules(self):
        owned = [r for mod in CHECKERS for r in mod.RULES]
        assert sorted(owned) == self.EXPECTED_RULES[1:]  # HS001 is core's
        assert len(owned) == len(set(owned))

    def test_finding_schema_is_stable(self):
        assert FINDING_FIELDS == ("rule", "path", "line", "message", "suppressed")
        f = Finding("HS999", "pkg/x.py", 3, "msg")
        assert f.to_dict() == {
            "rule": "HS999",
            "path": "pkg/x.py",
            "line": 3,
            "message": "msg",
            "suppressed": False,
        }
        assert f.render() == "pkg/x.py:3: HS999 msg"


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


class TestCli:
    def _run(self, *args):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "hyperspace_tpu.analysis", *args],
            capture_output=True,
            text=True,
            env=env,
            cwd=os.path.dirname(PKG_DIR),
            timeout=120,
        )

    def test_exit_nonzero_on_violation(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(
            pkg,
            {
                "m.py": """
                    def f():
                        try:
                            return 1
                        except:
                            return None
                """
            },
        )
        proc = self._run(str(pkg))
        assert proc.returncode == 1
        assert "HS401" in proc.stdout

    def test_exit_zero_on_clean_tree(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, {"m.py": "def f():\n    return 1\n"})
        proc = self._run(str(pkg))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_list_rules(self, tmp_path):
        proc = self._run("--list-rules")
        assert proc.returncode == 0
        for rule in TestGolden.EXPECTED_RULES:
            assert rule in proc.stdout

    def test_witness_clean_exits_zero(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, {"m.py": "def f():\n    return 1\n"})
        wit = tmp_path / "wit.json"
        wit.write_text('{"version": 1, "locks": {}, "edges": []}')
        proc = self._run(str(pkg), "--witness", str(wit))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_witness_model_gap_exits_one(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, {"m.py": "def f():\n    return 1\n"})
        wit = tmp_path / "wit.json"
        wit.write_text(
            '{"version": 1, "locks": {"ghost.py::_x": 1}, "edges": []}'
        )
        proc = self._run(str(pkg), "--witness", str(wit))
        assert proc.returncode == 1
        assert "HS604" in proc.stdout

    def test_witness_malformed_exits_two(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, {"m.py": "def f():\n    return 1\n"})
        wit = tmp_path / "wit.json"
        wit.write_text("{not json")
        proc = self._run(str(pkg), "--witness", str(wit))
        assert proc.returncode == 2
        proc = self._run(str(pkg), "--witness", str(tmp_path / "absent.json"))
        assert proc.returncode == 2

    def test_collective_witness_clean_exits_zero(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, {"collectives.py": SPMD_REGISTRY, "comm.py": SPMD_COMM})
        seq = [_rec("pkg.comm.exchange")]
        _cw_artifact(tmp_path, 0, seq)
        prefix = _cw_artifact(tmp_path, 1, seq)
        proc = self._run(str(pkg), "--witness", prefix)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_collective_witness_divergence_exits_one(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, {"collectives.py": SPMD_REGISTRY, "comm.py": SPMD_COMM})
        _cw_artifact(tmp_path, 0, [_rec("pkg.comm.exchange")])
        prefix = _cw_artifact(tmp_path, 1, [])
        proc = self._run(str(pkg), "--witness", prefix)
        assert proc.returncode == 1
        assert "HS804" in proc.stdout

    def test_residency_witness_clean_exits_zero(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, RES_FILES)
        wit = tmp_path / "res.json"
        wit.write_text(
            '{"version": 1, "sites": {"pkg.io.reader.load_table": '
            '{"peak_bytes": 10, "calls": 1}}, '
            '"budgets": {"cache-governed": 100}}'
        )
        proc = self._run(str(pkg), "--witness", str(wit))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        # the never-driven registered site warns on stderr
        assert "never witnessed" in proc.stderr

    def test_residency_witness_model_gap_exits_one(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, {"m.py": "def f():\n    return 1\n"})
        wit = tmp_path / "res.json"
        wit.write_text(
            '{"version": 1, "sites": {"ghost.mod.fn": '
            '{"peak_bytes": 7, "calls": 1}}}'
        )
        proc = self._run(str(pkg), "--witness", str(wit))
        assert proc.returncode == 1
        assert "HS1004" in proc.stdout

    def test_residency_witness_budget_breach_exits_one(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, RES_FILES)
        wit = tmp_path / "res.json"
        wit.write_text(
            '{"version": 1, "sites": {"pkg.io.reader.load_table": '
            '{"peak_bytes": 101, "calls": 1}}, '
            '"budgets": {"cache-governed": 100}}'
        )
        proc = self._run(str(pkg), "--witness", str(wit))
        assert proc.returncode == 1
        assert "HS1004" in proc.stdout
        assert "ceiling" in proc.stdout

    def test_residency_witness_malformed_exits_two(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, {"m.py": "def f():\n    return 1\n"})
        wit = tmp_path / "res.json"
        wit.write_text('{"version": 1, "sites": {"x": 3}}')
        proc = self._run(str(pkg), "--witness", str(wit))
        assert proc.returncode == 2

    def test_both_witness_kinds_in_one_run(self, tmp_path):
        # --witness is repeatable: one lock artifact + one residency
        # artifact + one collective family, each dispatched by content
        pkg = tmp_path / "pkg"
        _write_tree(pkg, {"collectives.py": SPMD_REGISTRY, "comm.py": SPMD_COMM})
        lock_wit = tmp_path / "locks.json"
        lock_wit.write_text('{"version": 1, "locks": {}, "edges": []}')
        res_wit = tmp_path / "res.json"
        res_wit.write_text('{"version": 1, "sites": {}}')
        seq = [_rec("pkg.comm.exchange")]
        _cw_artifact(tmp_path, 0, seq)
        prefix = _cw_artifact(tmp_path, 1, seq)
        proc = self._run(
            str(pkg),
            "--witness",
            str(lock_wit),
            "--witness",
            str(res_wit),
            "--witness",
            prefix,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
