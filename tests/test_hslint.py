"""hslint (hyperspace_tpu/analysis) — tier-1 gate + checker self-tests.

Three layers:

* the GATE: the analyzer over the real package must report zero
  unsuppressed findings (every rule violation on the tree is either
  fixed or carries a justified ``# hslint: disable``);
* fixture-based unit tests per checker: a seeded violation is caught,
  a suppression comment silences it, and a clean tree stays clean;
* golden stability: the ruleset and the finding schema are part of the
  repo's contract (CI configs and suppression comments reference rule
  ids), so changing them must be a deliberate act.
"""

import os
import subprocess
import sys
import textwrap

import pytest

import hyperspace_tpu
from hyperspace_tpu.analysis import (
    ALL_RULES,
    CHECKERS,
    FINDING_FIELDS,
    Finding,
    run_analysis,
)

PKG_DIR = os.path.dirname(os.path.abspath(hyperspace_tpu.__file__))
TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _write_tree(root, files):
    for rel, text in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))


def _lint(tmp_path, files, tests=None):
    """Unsuppressed findings for a fixture package tree."""
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    _write_tree(pkg, files)
    tests_dir = None
    if tests is not None:
        tdir = tmp_path / "tests"
        tdir.mkdir(exist_ok=True)
        _write_tree(tdir, tests)
        tests_dir = str(tdir)
    findings = run_analysis(str(pkg), tests_dir=tests_dir)
    return [f for f in findings if not f.suppressed]


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# The gate
# ---------------------------------------------------------------------------


class TestPackageClean:
    def test_no_unsuppressed_findings(self):
        findings = run_analysis(PKG_DIR, tests_dir=TESTS_DIR)
        active = [f for f in findings if not f.suppressed]
        assert not active, "unsuppressed hslint findings:\n" + "\n".join(
            f.render() for f in active
        )

    def test_analyzer_covers_real_surfaces(self):
        """The gate is only meaningful if the checkers engage: the real
        tree must contain native exports, actions, and traced functions
        for them to look at (guards against a silent no-op analyzer)."""
        from hyperspace_tpu.analysis.core import Project
        from hyperspace_tpu.analysis import kernel_parity, log_state, purity

        project = Project(PKG_DIR, tests_dir=TESTS_DIR)
        with open(project.native_cpp_path()) as f:
            exports = kernel_parity.cpp_exports(f.read())
        assert len(exports) >= 5
        machine, _ = log_state._extract_machine(project)
        assert machine.rollback and machine.stable
        traced = [
            fn.name
            for _rel, sf in project.files_under(*purity.HOT_DIRS)
            if sf.tree is not None
            for fn in purity._traced_functions(sf.tree)
        ]
        assert len(traced) >= 5


# ---------------------------------------------------------------------------
# Checker 1: kernel parity (HS1xx)
# ---------------------------------------------------------------------------


CPP = '''
    extern "C" {
    int hs_foo(const int* a, long long n) {
      return 0;
    }
    }  // extern "C"
'''

NATIVE_OK = '''
    KERNEL_TWINS = {
        "hs_foo": ("foo", "numpy.lexsort"),
    }

    def foo():
        return None
'''

CPP_FUSED = '''
    extern "C" {
    int64_t hs_fused_bar(const int* a, long long n) {
      return 0;
    }
    }  // extern "C"
'''


class TestKernelParity:
    def test_missing_registry_entry(self, tmp_path):
        files = {
            "native/hs_native.cpp": CPP,
            "native/__init__.py": "KERNEL_TWINS = {}\n",
        }
        assert "HS101" in _rules(_lint(tmp_path, files))

    def test_no_registry_at_all(self, tmp_path):
        files = {
            "native/hs_native.cpp": CPP,
            "native/__init__.py": "def foo():\n    return None\n",
        }
        assert "HS101" in _rules(_lint(tmp_path, files))

    def test_stale_entry_and_unresolved_twin(self, tmp_path):
        files = {
            "native/hs_native.cpp": CPP,
            "native/__init__.py": (
                "KERNEL_TWINS = {\n"
                '    "hs_foo": ("missing_wrapper", "pkg.nowhere.fn"),\n'
                '    "hs_gone": ("foo", "numpy.lexsort"),\n'
                "}\n"
                "def foo():\n    return None\n"
            ),
        }
        rules = _rules(_lint(tmp_path, files))
        assert "HS102" in rules and "HS103" in rules

    def test_missing_differential_test(self, tmp_path):
        files = {"native/hs_native.cpp": CPP, "native/__init__.py": NATIVE_OK}
        findings = _lint(
            tmp_path, files, tests={"test_other.py": "def test_x():\n    pass\n"}
        )
        assert "HS104" in _rules(findings)

    def test_clean(self, tmp_path):
        files = {"native/hs_native.cpp": CPP, "native/__init__.py": NATIVE_OK}
        findings = _lint(
            tmp_path,
            files,
            tests={"test_foo.py": "def test_foo():\n    assert foo\n"},
        )
        assert findings == []

    def test_fused_export_with_numpy_twin_flagged(self, tmp_path):
        # seeded violation: a fused-pipeline export registered against a
        # numpy single-op twin — HS105 requires the in-package
        # interpreted chain as the parity reference
        files = {
            "native/hs_native.cpp": CPP_FUSED,
            "native/__init__.py": (
                "KERNEL_TWINS = {\n"
                '    "hs_fused_bar": ("fused_bar", "numpy.lexsort"),\n'
                "}\n"
                "def fused_bar():\n    return None\n"
            ),
        }
        findings = _lint(
            tmp_path,
            files,
            tests={"test_bar.py": "def test_bar():\n    assert fused_bar\n"},
        )
        assert "HS105" in _rules(findings)

    def test_fused_export_with_interpreted_twin_clean(self, tmp_path):
        files = {
            "native/hs_native.cpp": CPP_FUSED,
            "native/__init__.py": (
                "KERNEL_TWINS = {\n"
                '    "hs_fused_bar": ("fused_bar", "pkg.chain.interpreted_bar"),\n'
                "}\n"
                "def fused_bar():\n    return None\n"
            ),
            "chain.py": "def interpreted_bar():\n    return None\n",
        }
        findings = _lint(
            tmp_path,
            files,
            tests={"test_bar.py": "def test_bar():\n    assert fused_bar\n"},
        )
        assert findings == []


# ---------------------------------------------------------------------------
# Checker 2: log state machine (HS2xx)
# ---------------------------------------------------------------------------


CONSTANTS = '''
    class States:
        DOESNOTEXIST = "DOESNOTEXIST"
        CREATING = "CREATING"
        ACTIVE = "ACTIVE"
        DELETING = "DELETING"
        DELETED = "DELETED"

        STABLE_STATES = frozenset({ACTIVE, DELETED, DOESNOTEXIST})

        ROLLBACK = {
            CREATING: DOESNOTEXIST,
            DELETING: ACTIVE,
        }
'''

ACTIONS_CLEAN = '''
    from pkg.constants import States

    class CreateAction:
        transient_state = States.CREATING
        final_state = States.ACTIVE

    class DeleteAction:
        transient_state = States.DELETING
        final_state = States.DELETED
        required_state = States.ACTIVE
'''


class TestLogStateMachine:
    def test_clean(self, tmp_path):
        files = {"constants.py": CONSTANTS, "actions/act.py": ACTIONS_CLEAN}
        assert _lint(tmp_path, files) == []

    def test_illegal_transient_without_rollback(self, tmp_path):
        # seeded illegal transition: ACTIVE used as a transient state —
        # there is no rollback edge, cancel() could never recover it
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": ACTIONS_CLEAN,
            "actions/bad.py": """
                from pkg.constants import States

                class BadAction:
                    transient_state = States.ACTIVE
                    final_state = States.ACTIVE
            """,
        }
        assert "HS201" in _rules(_lint(tmp_path, files))

    def test_commit_to_unstable_state(self, tmp_path):
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": ACTIONS_CLEAN,
            "actions/bad.py": """
                from pkg.constants import States

                class BadAction:
                    transient_state = States.CREATING
                    final_state = States.DELETING
            """,
        }
        assert "HS202" in _rules(_lint(tmp_path, files))

    def test_unknown_state_name(self, tmp_path):
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": ACTIONS_CLEAN
            + "\n    BOGUS = States.FROBNICATING\n",
        }
        assert "HS203" in _rules(_lint(tmp_path, files))

    def test_required_state_mismatch(self, tmp_path):
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": ACTIONS_CLEAN,
            "actions/bad.py": """
                from pkg.constants import States

                class BadAction:
                    transient_state = States.CREATING
                    final_state = States.ACTIVE
                    required_state = States.ACTIVE
            """,
        }
        assert "HS204" in _rules(_lint(tmp_path, files))

    def test_unused_rollback_state(self, tmp_path):
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": """
                from pkg.constants import States

                class CreateAction:
                    transient_state = States.CREATING
                    final_state = States.ACTIVE
            """,
        }
        assert "HS205" in _rules(_lint(tmp_path, files))

    def test_suppression(self, tmp_path):
        files = {
            "constants.py": CONSTANTS,
            "actions/act.py": ACTIONS_CLEAN,
            "actions/bad.py": """
                from pkg.constants import States

                class BadAction:
                    transient_state = States.ACTIVE  # hslint: disable=HS201
                    final_state = States.ACTIVE
            """,
        }
        assert _lint(tmp_path, files) == []


# ---------------------------------------------------------------------------
# Checker 3: hot-path purity (HS3xx)
# ---------------------------------------------------------------------------


class TestPurity:
    def test_numpy_in_jit(self, tmp_path):
        files = {
            "ops/k.py": """
                import jax
                import jax.numpy as jnp
                import numpy as np

                @jax.jit
                def bad(x):
                    return np.concatenate([x, x])
            """
        }
        assert "HS301" in _rules(_lint(tmp_path, files))

    def test_host_sync_in_jit(self, tmp_path):
        files = {
            "ops/k.py": """
                import jax

                @jax.jit
                def bad(x):
                    return x.item()
            """
        }
        assert "HS302" in _rules(_lint(tmp_path, files))

    def test_shard_map_by_name_and_partial_jit(self, tmp_path):
        files = {
            "parallel/k.py": """
                import functools
                import jax
                import numpy as np
                from jax.experimental.shard_map import shard_map

                def local(x):
                    return np.argsort(x)

                def run(mesh, x):
                    return shard_map(local, mesh=mesh)(x)

                @functools.partial(jax.jit, static_argnames=("n",))
                def also_bad(x, n):
                    return np.asarray(x)
            """
        }
        findings = _lint(tmp_path, files)
        assert "HS301" in _rules(findings)  # np.argsort in shard_map'd fn
        assert "HS302" in _rules(findings)  # np.asarray under jit

    def test_clean_and_allowlist(self, tmp_path):
        files = {
            "ops/k.py": """
                import jax
                import jax.numpy as jnp
                import numpy as np

                @jax.jit
                def good(x):
                    return jnp.sum(x) + np.uint32(1)

                def host_helper(x):
                    # not traced: host numpy is fine here
                    return np.asarray(x).item()
            """
        }
        assert _lint(tmp_path, files) == []

    def test_suppression(self, tmp_path):
        files = {
            "ops/k.py": """
                import jax
                import numpy as np

                @jax.jit
                def bad(x):
                    # callback runs host-side by contract here
                    return np.log(x)  # hslint: disable=HS301
            """
        }
        assert _lint(tmp_path, files) == []

    def test_suppression_with_inline_justification(self, tmp_path):
        # text after the rule id must not break the suppression match
        files = {
            "ops/k.py": """
                import jax
                import numpy as np

                @jax.jit
                def bad(x):
                    return np.log(x)  # hslint: disable=HS301 host cb contract
            """
        }
        assert _lint(tmp_path, files) == []

    def test_annotations_are_not_traced(self, tmp_path):
        # np.ndarray annotations evaluate at def time, never under trace
        files = {
            "ops/k.py": """
                import jax
                import jax.numpy as jnp
                import numpy as np

                @jax.jit
                def good(x: np.ndarray) -> np.ndarray:
                    y: np.ndarray = jnp.sum(x)
                    return y
            """
        }
        assert _lint(tmp_path, files) == []


# ---------------------------------------------------------------------------
# Checker 4: exception policy (HS4xx)
# ---------------------------------------------------------------------------


class TestExceptPolicy:
    def test_bare_except(self, tmp_path):
        files = {
            "m.py": """
                def f():
                    try:
                        return 1
                    except:
                        return None
            """
        }
        assert "HS401" in _rules(_lint(tmp_path, files))

    def test_broad_except_without_reraise(self, tmp_path):
        files = {
            "m.py": """
                def f():
                    try:
                        return 1
                    except Exception:
                        return None
            """
        }
        assert "HS402" in _rules(_lint(tmp_path, files))

    def test_reraise_is_allowed(self, tmp_path):
        files = {
            "m.py": """
                def f():
                    try:
                        return 1
                    except Exception as e:
                        print(e)
                        raise
            """
        }
        assert _lint(tmp_path, files) == []

    def test_typed_is_clean_and_suppression_works(self, tmp_path):
        files = {
            "m.py": """
                def f():
                    try:
                        return 1
                    except ValueError:
                        return None

                def g():
                    try:
                        return 1
                    # deliberate catch-all: fallback is the contract
                    except Exception:  # hslint: disable=HS402
                        return None
            """
        }
        assert _lint(tmp_path, files) == []


# ---------------------------------------------------------------------------
# Checker 5: locks (HS5xx)
# ---------------------------------------------------------------------------


class TestLocks:
    def test_seeded_lock_order_cycle(self, tmp_path):
        files = {
            "a.py": """
                import threading

                A = threading.Lock()
                B = threading.Lock()

                def f():
                    with A:
                        with B:
                            pass

                def g():
                    with B:
                        with A:
                            pass
            """
        }
        assert "HS501" in _rules(_lint(tmp_path, files))

    def test_cross_function_cycle(self, tmp_path):
        # f holds A and calls helper() which takes B; g does the reverse
        # through its own callee — only the transitive call graph sees it
        files = {
            "a.py": """
                import threading

                A = threading.Lock()
                B = threading.Lock()

                def take_b():
                    with B:
                        pass

                def take_a():
                    with A:
                        pass

                def f():
                    with A:
                        take_b()

                def g():
                    with B:
                        take_a()
            """
        }
        assert "HS501" in _rules(_lint(tmp_path, files))

    def test_lock_held_io_direct_and_via_callee(self, tmp_path):
        files = {
            "a.py": """
                import threading

                A = threading.Lock()

                def io_helper(p):
                    with open(p) as f:
                        return f.read()

                def direct(p):
                    with A:
                        return open(p).read()

                def via_callee(p):
                    with A:
                        return io_helper(p)
            """
        }
        findings = [f for f in _lint(tmp_path, files) if f.rule == "HS502"]
        assert len(findings) == 2

    def test_consistent_order_is_clean(self, tmp_path):
        files = {
            "a.py": """
                import threading

                A = threading.Lock()
                B = threading.Lock()

                def f():
                    with A:
                        with B:
                            pass

                def g():
                    with A:
                        with B:
                            pass
            """
        }
        assert _lint(tmp_path, files) == []

    def test_same_class_name_in_two_modules_does_not_alias(self, tmp_path):
        # instance locks are keyed by (module, class): two classes both
        # named Cache must be distinct lock identities, or their edges
        # would merge and could fake a cycle across unrelated modules
        from hyperspace_tpu.analysis.core import Project
        from hyperspace_tpu.analysis.locks import _collect_defs

        src = """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
        """
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        _write_tree(pkg, {"a.py": src, "b.py": src})
        _indexes, locks = _collect_defs(Project(str(pkg)))
        assert len(locks) == 2
        assert {scope for scope, _ in locks} == {
            "cls:a.py:Cache",
            "cls:b.py:Cache",
        }

    def test_instance_locks_and_suppression(self, tmp_path):
        files = {
            "a.py": """
                import threading

                class Cache:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def load(self, p):
                        # one-time load is serialized by design
                        with self._lock:  # hslint: disable=HS502
                            return open(p).read()

                    def get(self, k):
                        with self._lock:
                            return k
            """
        }
        assert _lint(tmp_path, files) == []


# ---------------------------------------------------------------------------
# Golden: ruleset + finding schema stability
# ---------------------------------------------------------------------------


class TestGolden:
    EXPECTED_RULES = [
        "HS001",
        "HS101",
        "HS102",
        "HS103",
        "HS104",
        "HS105",
        "HS201",
        "HS202",
        "HS203",
        "HS204",
        "HS205",
        "HS301",
        "HS302",
        "HS401",
        "HS402",
        "HS501",
        "HS502",
    ]

    def test_ruleset_is_stable(self):
        assert sorted(ALL_RULES) == self.EXPECTED_RULES
        for rule, desc in ALL_RULES.items():
            assert desc and isinstance(desc, str)

    def test_every_checker_owns_rules(self):
        owned = [r for mod in CHECKERS for r in mod.RULES]
        assert sorted(owned) == self.EXPECTED_RULES[1:]  # HS001 is core's
        assert len(owned) == len(set(owned))

    def test_finding_schema_is_stable(self):
        assert FINDING_FIELDS == ("rule", "path", "line", "message", "suppressed")
        f = Finding("HS999", "pkg/x.py", 3, "msg")
        assert f.to_dict() == {
            "rule": "HS999",
            "path": "pkg/x.py",
            "line": 3,
            "message": "msg",
            "suppressed": False,
        }
        assert f.render() == "pkg/x.py:3: HS999 msg"


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


class TestCli:
    def _run(self, *args):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "hyperspace_tpu.analysis", *args],
            capture_output=True,
            text=True,
            env=env,
            cwd=os.path.dirname(PKG_DIR),
            timeout=120,
        )

    def test_exit_nonzero_on_violation(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(
            pkg,
            {
                "m.py": """
                    def f():
                        try:
                            return 1
                        except:
                            return None
                """
            },
        )
        proc = self._run(str(pkg))
        assert proc.returncode == 1
        assert "HS401" in proc.stdout

    def test_exit_zero_on_clean_tree(self, tmp_path):
        pkg = tmp_path / "pkg"
        _write_tree(pkg, {"m.py": "def f():\n    return 1\n"})
        proc = self._run(str(pkg))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_list_rules(self, tmp_path):
        proc = self._run("--list-rules")
        assert proc.returncode == 0
        for rule in TestGolden.EXPECTED_RULES:
            assert rule in proc.stdout
