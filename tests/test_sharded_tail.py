"""Sharded build/serve tail (``hyperspace.build.shardedTail.enabled``) —
differential tests on the simulated 8-device CPU mesh.

The contract: with the flag on, each mesh shard runs the post-exchange
build tail (partition-first sort + bucketed parquet write) and the serve
tail (prepare + merge-join) over only the buckets it owns
(``bucket % D``), concurrently with the other shards — and every output
is BIT-IDENTICAL to the single-tail path (flag off): same parquet bytes
per bucket file, same joined rows in the same order. A bucket lives
wholly inside one shard, so the per-bucket stable sort/merge cannot
observe the sharding; these tests make that argument mechanical.
"""

import hashlib
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig


@pytest.fixture
def mesh8(session_factory):
    return session_factory(8)


@pytest.fixture
def mixed_parquet(tmp_path):
    """Heavily tied keys (stability torture) + a string column + a
    NULLABLE float payload (validity masks must survive the exchange and
    the per-shard tail)."""
    rng = np.random.default_rng(17)
    d = tmp_path / "mixed"
    d.mkdir()
    for i in range(4):
        n = 3000
        vals = rng.normal(size=n)
        t = pa.table(
            {
                "k": pa.array(rng.integers(0, 5, n), type=pa.int64()),
                "s": pa.array(
                    [["aa", "bb", "cc"][v] for v in rng.integers(0, 3, n)]
                ),
                "v": pa.array(
                    [None if j % 13 == 0 else vals[j] for j in range(n)],
                    type=pa.float64(),
                ),
            }
        )
        pq.write_table(t, d / f"part-{i}.parquet")
    return str(d)


def _sha(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _assert_identical_files(files_a, files_b):
    assert [os.path.basename(f) for f in files_a] == [
        os.path.basename(f) for f in files_b
    ]
    for fa, fb in zip(files_a, files_b):
        assert _sha(fa) == _sha(fb), f"parquet bytes differ: {fa} vs {fb}"


def _build(session, src, name, sharded, budget=0, lineage=False):
    session.conf.set(C.BUILD_SHARDED_TAIL_ENABLED, sharded)
    session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, budget)
    session.conf.set(C.INDEX_LINEAGE_ENABLED, lineage)
    hs = Hyperspace(session)
    df = session.read.parquet(src)
    hs.create_index(df, CoveringIndexConfig(name, ["k"], ["s", "v"]))
    entry = session.index_manager.get_index_log_entry(name)
    return sorted(entry.content.files)


class TestShardedBuildDifferential:
    def test_in_memory_bit_identical(self, mesh8, mixed_parquet):
        on = _build(mesh8, mixed_parquet, "shon", True)
        off = _build(mesh8, mixed_parquet, "shoff", False)
        _assert_identical_files(on, off)
        # the sharded tail actually ran per shard
        from hyperspace_tpu.indexes.covering_build import (
            last_build_breakdown,
        )

        on2 = _build(mesh8, mixed_parquet, "shon2", True)
        assert last_build_breakdown.get("tail_shards", 0) > 1
        _assert_identical_files(on, on2)

    def test_streaming_waves_bit_identical(self, mesh8, mixed_parquet):
        """Budget-capped builds wave/spill/merge; the per-wave sharded
        sort and the per-shard merge fan-out must land the same bytes."""
        from hyperspace_tpu.indexes.covering_build import (
            per_file_materialized_bytes,
        )

        first = sorted(os.listdir(mixed_parquet))[0]
        per_file = per_file_materialized_bytes(
            [os.path.join(mixed_parquet, first)], "parquet"
        )[0]
        budget = int(per_file * 2.5)
        on = _build(mesh8, mixed_parquet, "ston", True, budget=budget)
        off = _build(mesh8, mixed_parquet, "stoff", False, budget=budget)
        _assert_identical_files(on, off)

    def test_refresh_incremental_bit_identical(self, mesh8, mixed_parquet):
        def run(name, sharded):
            _build(mesh8, mixed_parquet, name, sharded, lineage=True)
            hs = Hyperspace(mesh8)
            rng = np.random.default_rng(5)
            extra = pa.table(
                {
                    "k": pa.array(
                        rng.integers(0, 5, 500), type=pa.int64()
                    ),
                    "s": pa.array(["dd"] * 500),
                    "v": pa.array(rng.normal(size=500)),
                }
            )
            extra_path = os.path.join(
                mixed_parquet, f"extra-{name}.parquet"
            )
            pq.write_table(extra, extra_path)
            mesh8.index_manager.clear_cache()
            hs.refresh_index(name, C.REFRESH_MODE_INCREMENTAL)
            os.remove(extra_path)  # identical source for the next leg
            mesh8.index_manager.clear_cache()
            entry = mesh8.index_manager.get_index_log_entry(name)
            return sorted(entry.content.files)

        on = run("rfon", True)
        off = run("rfoff", False)
        _assert_identical_files(on, off)

    def test_cross_mesh_serve(self, session_factory, mixed_parquet):
        """An index built by the sharded tail serves identically from a
        single-device session (layout is mesh-independent)."""
        _build(session_factory(8), mixed_parquet, "xms", True)
        server = session_factory(1)
        df = server.read.parquet(mixed_parquet)
        q = lambda d: d.filter(d["k"] == 2).select("k", "s", "v")
        server.disable_hyperspace()
        base = q(df).collect()
        server.enable_hyperspace()
        assert "Hyperspace(Type: CI" in q(df).explain()
        got = q(df).collect()
        key = lambda t: t.sort_by(
            [(c, "ascending") for c in t.column_names]
        )
        assert key(got).equals(key(base))
        assert got.num_rows > 0


@pytest.fixture
def join_data(tmp_path):
    rng = np.random.default_rng(23)
    fact = tmp_path / "fact"
    dim = tmp_path / "dim"
    fact.mkdir()
    dim.mkdir()
    for i in range(3):
        n = 4000
        t = pa.table(
            {
                "k": pa.array(rng.integers(0, 100, n), type=pa.int64()),
                "p": pa.array(rng.normal(size=n)),
            }
        )
        pq.write_table(t, fact / f"f{i}.parquet")
    pq.write_table(
        pa.table(
            {
                "j": pa.array(np.arange(100), type=pa.int64()),
                "w": pa.array(rng.normal(size=100)),
            }
        ),
        dim / "d.parquet",
    )
    return str(fact), str(dim)


class TestShardedServeDifferential:
    def _indexed(self, session, fact, dim):
        hs = Hyperspace(session)
        f = session.read.parquet(fact)
        d = session.read.parquet(dim)
        hs.create_index(f, CoveringIndexConfig("fidx", ["k"], ["p"]))
        hs.create_index(d, CoveringIndexConfig("didx", ["j"], ["w"]))
        return f, d

    @staticmethod
    def _q(f, d):
        return f.join(d, on=f["k"] == d["j"]).select("k", "p", "w")

    def test_join_bit_identical(self, mesh8, join_data):
        f, d = self._indexed(mesh8, *join_data)
        mesh8.enable_hyperspace()
        assert self._q(f, d).explain().count("Hyperspace(Type: CI") == 2
        mesh8.conf.set(C.BUILD_SHARDED_TAIL_ENABLED, True)
        on = self._q(f, d).collect()
        mesh8.conf.set(C.BUILD_SHARDED_TAIL_ENABLED, False)
        off = self._q(f, d).collect()
        # bit-identical: same rows in the same order, not just same set
        assert on.equals(off)
        mesh8.disable_hyperspace()
        base = self._q(f, d).collect()
        key = lambda t: t.sort_by(
            [(c, "ascending") for c in t.column_names]
        )
        assert key(on).equals(key(base))
        assert on.num_rows > 0

    def test_hybrid_delta_bit_identical(self, mesh8, join_data):
        fact, dim = join_data
        f, d = self._indexed(mesh8, fact, dim)
        pq.write_table(
            pa.table(
                {
                    # one key beyond the dim range: delta-only bucket rows
                    "k": pa.array([0, 1, 2, 300], type=pa.int64()),
                    "p": pa.array([1.0, 2.0, 3.0, 4.0]),
                }
            ),
            os.path.join(fact, "extra.parquet"),
        )
        mesh8.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        mesh8.index_manager.clear_cache()
        f2 = mesh8.read.parquet(fact)
        mesh8.enable_hyperspace()
        assert self._q(f2, d).explain().count("Hyperspace(Type: CI") == 2
        mesh8.conf.set(C.BUILD_SHARDED_TAIL_ENABLED, True)
        on = self._q(f2, d).collect()
        mesh8.conf.set(C.BUILD_SHARDED_TAIL_ENABLED, False)
        off = self._q(f2, d).collect()
        assert on.equals(off)
        mesh8.disable_hyperspace()
        base = self._q(f2, d).collect()
        key = lambda t: t.sort_by(
            [(c, "ascending") for c in t.column_names]
        )
        assert key(on).equals(key(base))


class TestShardedSortPermutation:
    @pytest.mark.parametrize("n,nb,k", [(0, 8, 1), (9, 3, 2), (60_000, 8, 1)])
    def test_per_bucket_equals_global(self, n, nb, k):
        """Shard-major output differs in GLOBAL order from the global
        (bucket, keys) sort by design; restricted to any bucket the two
        are identical — the only order the bucketed writers observe."""
        from hyperspace_tpu.ops.sort import (
            sharded_sort_permutation,
            sort_permutation,
        )

        rng = np.random.default_rng(n + nb + k)
        D = 4
        reps = rng.integers(-(2**60), 2**60, size=(k, n), dtype=np.int64)
        # shard-major layout with bucket % D ownership, as post-exchange
        owner = rng.integers(0, D, n)
        order = np.argsort(owner, kind="stable")
        reps = reps[:, order]
        owner = owner[order]
        buckets = np.empty(n, dtype=np.int32)
        for s in range(D):
            m = owner == s
            buckets[m] = (
                rng.integers(0, max(nb // D, 1), int(m.sum())) * D + s
            ) % nb
        shard_offs = np.concatenate(
            [[0], np.cumsum(np.bincount(owner, minlength=D))]
        ).astype(np.int64)
        perm = sharded_sort_permutation(reps, buckets, nb, shard_offs)
        ref = sort_permutation(reps, buckets)
        for b in np.unique(buckets):
            np.testing.assert_array_equal(
                perm[buckets[perm] == b], ref[buckets[ref] == b]
            )


class TestSkewTelemetry:
    def test_skew_recorded_and_warned(self, mesh8, tmp_path, caplog):
        """All rows hashing into one bucket → one hot (shard, peer) slot;
        telemetry must record the ratio and the warning must fire."""
        import logging

        d = tmp_path / "skew"
        d.mkdir()
        # enough rows that every shard's send to the one hot peer clears
        # the warn floor (BUILD_SHUFFLE_SKEW_WARN_MIN_ROWS)
        n = 20000
        t = pa.table(
            {
                "k": pa.array(np.full(n, 7), type=pa.int64()),
                "s": pa.array(["x"] * n),
                "v": pa.array(np.ones(n)),
            }
        )
        pq.write_table(t, d / "p0.parquet")
        pq.write_table(t, d / "p1.parquet")
        with caplog.at_level(logging.WARNING, "hyperspace_tpu.shuffle"):
            _build(mesh8, str(d), "skidx", True)
        from hyperspace_tpu.indexes.covering_build import (
            last_build_telemetry,
        )

        assert last_build_telemetry["shuffle_skew_ratio"] >= (
            C.BUILD_SHUFFLE_SKEW_WARN_RATIO
        )
        assert any("shuffle skew" in r.message for r in caplog.records)

    def test_balanced_no_warning(self, mesh8, mixed_parquet, caplog):
        import logging

        with caplog.at_level(logging.WARNING, "hyperspace_tpu.shuffle"):
            # 5 keys over 8 buckets is mildly skewed but telemetry must
            # exist either way
            _build(mesh8, mixed_parquet, "balidx", True)
        from hyperspace_tpu.indexes.covering_build import (
            last_build_telemetry,
        )

        assert "shuffle_skew_ratio" in last_build_telemetry
        assert last_build_telemetry["shuffle_devices"] == 8.0


class TestNativeTmpSweep:
    def test_stale_tmp_and_superseded_swept(self, tmp_path):
        """Week-old compile scratch files are reclaimed on cleanup —
        including the CURRENT revision's own orphans — while live
        artifacts and fresh tmps (possibly another process mid-compile)
        survive."""
        import time

        from hyperspace_tpu.native import _SUPERSEDED_TTL_S, _cleanup_superseded

        keep = tmp_path / "_hs_native_aaaa.so"
        stale = time.time() - _SUPERSEDED_TTL_S - 60
        files = {
            "_hs_native_aaaa.so": None,  # current revision: keep
            "_hs_native_aaaa.so.failed": None,  # current marker: keep
            "_hs_native_aaaa.so.tmp.123": stale,  # own orphan: sweep
            "_hs_native_bbbb.so.tmp.9": stale,  # foreign orphan: sweep
            "_hs_native_bbbb.so": stale,  # superseded revision: sweep
            "_hs_native_cccc.so": None,  # fresh foreign .so: keep
            "_hs_native_cccc.so.tmp.7": None,  # mid-compile tmp: keep
        }
        for name, mtime in files.items():
            p = tmp_path / name
            p.write_bytes(b"x")
            if mtime is not None:
                os.utime(p, (mtime, mtime))
        _cleanup_superseded(str(keep))
        left = sorted(os.listdir(tmp_path))
        assert left == [
            "_hs_native_aaaa.so",
            "_hs_native_aaaa.so.failed",
            "_hs_native_cccc.so",
            "_hs_native_cccc.so.tmp.7",
        ]


class TestShardMapBodyLint:
    def test_parallel_shard_map_bodies_hs3_clean(self):
        """HS3xx (hot-path purity) over the mesh/shuffle modules: the
        shard_map program bodies the sharded tail feeds must stay
        device-pure (no host numpy / syncs under trace)."""
        import hyperspace_tpu
        from hyperspace_tpu.analysis import run_analysis

        pkg = os.path.dirname(os.path.abspath(hyperspace_tpu.__file__))
        findings = [
            f
            for f in run_analysis(pkg)
            if f.rule.startswith("HS3") and not f.suppressed
        ]
        assert findings == [], findings
