from hyperspace_tpu import constants as C
from hyperspace_tpu.config import CacheWithTransform, Config


def test_defaults():
    conf = Config()
    assert conf.apply_enabled is True
    assert conf.num_buckets == 200
    assert conf.lineage_enabled is False
    assert conf.hybrid_scan_enabled is False
    assert conf.hybrid_scan_max_appended_ratio == 0.3
    assert conf.hybrid_scan_max_deleted_ratio == 0.2
    assert conf.optimize_file_size_threshold == 256 * 1024 * 1024


def test_set_get_typed():
    conf = Config()
    conf.set(C.INDEX_NUM_BUCKETS, "16")
    assert conf.num_buckets == 16
    conf.set(C.INDEX_LINEAGE_ENABLED, "true")
    assert conf.lineage_enabled is True
    conf.set(C.INDEX_LINEAGE_ENABLED, False)
    assert conf.lineage_enabled is False


def test_cache_with_transform_invalidates_on_change():
    conf = Config()
    calls = []

    def transform(c):
        calls.append(1)
        return c.num_buckets * 2

    cache = CacheWithTransform(conf, transform)
    assert cache.load() == 400
    assert cache.load() == 400
    assert len(calls) == 1
    conf.set(C.INDEX_NUM_BUCKETS, 10)
    assert cache.load() == 20
    assert len(calls) == 2
