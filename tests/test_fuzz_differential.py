"""Seeded differential fuzz: random predicates vs a pyarrow oracle.

The reference's strongest test pattern is differential ("same answer with
and without the index"); this extends it below the planner: randomly
generated predicates over randomly generated data must produce the same
row sets as pyarrow's compute kernels, on BOTH filter paths (host
evaluator and device kernel). Deterministic seeds keep failures
reproducible.
"""

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.io.columnar import ColumnarBatch
from hyperspace_tpu.ops.filter import Unsupported, device_filter_mask
from hyperspace_tpu.plan import expressions as E


def _random_table(rng, n=500):
    ints = rng.integers(-50, 50, n)
    int_nulls = rng.random(n) < 0.1
    flts = np.round(rng.normal(0, 10, n), 2)
    flt_nan = rng.random(n) < 0.05
    flts[flt_nan] = np.nan
    strs = rng.choice(["aa", "bb", "cc", "dd", None], n, p=[0.3, 0.3, 0.2, 0.1, 0.1])
    durs = rng.integers(-5000, 5000, n)  # milliseconds
    dur_nulls = rng.random(n) < 0.1
    return pa.table(
        {
            "i": pa.array(
                [None if m else int(v) for v, m in zip(ints, int_nulls)],
                type=pa.int64(),
            ),
            "f": pa.array(flts),
            "s": pa.array([s if s is None else str(s) for s in strs]),
            "d": pa.array(
                [None if m else int(v) for v, m in zip(durs, dur_nulls)],
                type=pa.duration("ms"),
            ),
        }
    )


def _random_pred(rng, depth=0):
    """(our Expr, pyarrow compute expr) pair with identical semantics."""
    kind = rng.choice(
        ["cmp_i", "cmp_f", "eq_s", "in_i", "isnull", "cmp_d", "and", "or", "not"]
        if depth < 3
        else ["cmp_i", "cmp_f", "eq_s", "in_i", "isnull", "cmp_d"]
    )
    f = pc.field
    if kind == "cmp_i":
        lit = int(rng.integers(-60, 60))
        op = rng.choice(["==", "!=", "<", "<=", ">", ">="])
        ours = {
            "==": E.Col("i") == lit,
            "!=": E.Col("i") != lit,
            "<": E.Col("i") < lit,
            "<=": E.Col("i") <= lit,
            ">": E.Col("i") > lit,
            ">=": E.Col("i") >= lit,
        }[op]
        theirs = {
            "==": f("i") == lit,
            "!=": f("i") != lit,
            "<": f("i") < lit,
            "<=": f("i") <= lit,
            ">": f("i") > lit,
            ">=": f("i") >= lit,
        }[op]
        return ours, theirs
    if kind == "cmp_f":
        lit = float(np.round(rng.normal(0, 10), 2))
        op = rng.choice(["<", ">="])
        if op == "<":
            return E.Col("f") < lit, f("f") < lit
        return E.Col("f") >= lit, f("f") >= lit
    if kind == "eq_s":
        lit = str(rng.choice(["aa", "bb", "zz"]))
        return E.Col("s") == lit, f("s") == lit
    if kind == "cmp_d":
        # duration literal at a RANDOM unit — coarser (s), matching (ms)
        # or finer (us, possibly between the column's ms ticks): the
        # engine's tick lowering must agree with pyarrow's exact
        # duration comparison in every case
        unit = str(rng.choice(["s", "ms", "us"]))
        scale = {"s": 5, "ms": 5000, "us": 5_000_500}[unit]
        lit = np.timedelta64(int(rng.integers(-scale, scale)), unit)
        op = rng.choice(["==", "<", ">="])
        ours = {
            "==": E.Col("d") == lit,
            "<": E.Col("d") < lit,
            ">=": E.Col("d") >= lit,
        }[op]
        sc = pa.scalar(lit)
        theirs = {
            "==": f("d") == sc,
            "<": f("d") < sc,
            ">=": f("d") >= sc,
        }[op]
        return ours, theirs
    if kind == "in_i":
        vals = [int(v) for v in rng.integers(-60, 60, 3)]
        # oracle NOTE: pyarrow's is_in maps NULL to false (so NOT IN would
        # wrongly keep null rows); SQL three-valued IN ≡ an OR-chain of
        # equalities, through which NULL propagates correctly
        theirs = f("i") == vals[0]
        for v in vals[1:]:
            theirs = theirs | (f("i") == v)
        return E.Col("i").isin(*vals), theirs
    if kind == "isnull":
        col = str(rng.choice(["i", "s"]))
        return E.IsNull(E.Col(col)), f(col).is_null()
    a_ours, a_theirs = _random_pred(rng, depth + 1)
    b_ours, b_theirs = _random_pred(rng, depth + 1)
    if kind == "and":
        return E.And(a_ours, b_ours), a_theirs & b_theirs
    if kind == "or":
        return E.Or(a_ours, b_ours), a_theirs | b_theirs
    return E.Not(a_ours), ~a_theirs


@pytest.mark.parametrize("seed", range(30))
def test_filter_matches_pyarrow_oracle(seed):
    rng = np.random.default_rng(seed)
    table = _random_table(rng)
    batch = ColumnarBatch.from_arrow(table)
    ours, theirs = _random_pred(rng)
    # compare by ROW INDEX (NaN-proof: tuple/row comparisons break on NaN)
    indexed = table.append_column(
        "_row", pa.array(np.arange(table.num_rows), type=pa.int64())
    )
    want_rows = indexed.filter(theirs).column("_row").to_pylist()
    host_mask = E.filter_mask(ours, batch)
    got_rows = np.nonzero(host_mask)[0].tolist()
    assert got_rows == want_rows, (
        f"host mismatch for {ours!r}: ours={got_rows[:10]}... "
        f"oracle={want_rows[:10]}..."
    )
    try:
        dev_mask = device_filter_mask(ours, batch)
    except Unsupported:
        return
    assert dev_mask.tolist() == host_mask.tolist(), (
        f"device/host mask divergence for {ours!r}"
    )
