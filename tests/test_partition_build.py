"""Partition-first build pipeline — differential tests.

The partition-then-sort pipeline (``hyperspace.index.build.partitionFirst``,
default on) must produce output BIT-IDENTICAL to the legacy global
lexsort by (bucket, keys...): same stable tie order, same lineage
values, same parquet bytes per bucket file (modulo nothing — the
encoding decision is shared), on both the in-memory and the
streaming/spill paths, with and without the native kernels.
"""

import hashlib
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.ops.sort import (
    partition_by_bucket,
    partitioned_sort_permutation,
    sort_permutation,
)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


@pytest.fixture
def tied_parquet(tmp_path):
    """4 files whose keys collide heavily (3 distinct values per column)
    — long tie runs across files, the stability torture case — plus a
    string column and a float payload."""
    rng = np.random.default_rng(21)
    d = tmp_path / "tied"
    d.mkdir()
    for i in range(4):
        n = 3000
        t = pa.table(
            {
                "k": pa.array(rng.integers(0, 3, n), type=pa.int64()),
                "s": pa.array(
                    [["aa", "bb", "cc"][v] for v in rng.integers(0, 3, n)]
                ),
                "v": pa.array(rng.normal(size=n)),
            }
        )
        pq.write_table(t, d / f"part-{i}.parquet")
    return str(d)


def _sha(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _build(session, hs, src, name, partition_first, budget=0, lineage=False):
    session.conf.set(C.INDEX_BUILD_PARTITION_FIRST, partition_first)
    session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, budget)
    session.conf.set(C.INDEX_LINEAGE_ENABLED, lineage)
    df = session.read.parquet(src)
    hs.create_index(df, CoveringIndexConfig(name, ["k"], ["s", "v"]))
    entry = session.index_manager.get_index_log_entry(name)
    return sorted(entry.content.files)


def _assert_identical_files(files_a, files_b):
    assert [os.path.basename(f) for f in files_a] == [
        os.path.basename(f) for f in files_b
    ]
    for fa, fb in zip(files_a, files_b):
        ta, tb = pq.read_table(fa), pq.read_table(fb)
        assert ta.equals(tb), f"row content/order differs: {fa} vs {fb}"
        assert _sha(fa) == _sha(fb), f"parquet bytes differ: {fa} vs {fb}"


class TestDifferentialBuild:
    def test_in_memory_bit_identical(self, session, hs, tied_parquet):
        legacy = _build(session, hs, tied_parquet, "leg", False)
        pfirst = _build(session, hs, tied_parquet, "pf", True)
        _assert_identical_files(legacy, pfirst)

    def test_lineage_bit_identical(self, session, hs, tied_parquet):
        """Lineage attaches a per-file constant column whose within-tie
        order is exactly what stability protects."""
        legacy = _build(session, hs, tied_parquet, "legl", False, lineage=True)
        pfirst = _build(session, hs, tied_parquet, "pfl", True, lineage=True)
        _assert_identical_files(legacy, pfirst)
        # lineage survives: every file id of the source is present
        t = pa.concat_tables([pq.read_table(f) for f in pfirst])
        assert len(set(t.column(C.DATA_FILE_NAME_ID).to_pylist())) == 4

    def test_streaming_spill_bit_identical(self, session, hs, tied_parquet):
        """Budget-constrained builds go through the wave/spill/merge loop;
        its per-wave bucketize must partition-first to the same layout."""
        from hyperspace_tpu.indexes.covering_build import (
            estimated_materialized_bytes,
        )

        per_file = estimated_materialized_bytes(
            [os.path.join(tied_parquet, sorted(os.listdir(tied_parquet))[0])],
            "parquet",
        )
        budget = int(per_file * 2.5)
        legacy = _build(session, hs, tied_parquet, "legs", False, budget=budget)
        pfirst = _build(session, hs, tied_parquet, "pfs", True, budget=budget)
        _assert_identical_files(legacy, pfirst)

    def test_numpy_leg_bit_identical(self, session, hs, tied_parquet, monkeypatch):
        """HS_NATIVE=0: the pure-numpy twins must reproduce the same
        bytes as the native kernels."""
        from hyperspace_tpu import native

        native_files = _build(session, hs, tied_parquet, "natv", True)
        monkeypatch.setenv("HS_NATIVE", "0")
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", False)
        numpy_files = _build(session, hs, tied_parquet, "nump", True)
        _assert_identical_files(native_files, numpy_files)

    def test_refresh_incremental_bit_identical(self, session, hs, tied_parquet):
        """The refresh data plane (append + delete compensation) rides
        the same writers; both paths must land the same new version."""

        def run(name, partition_first):
            files = _build(
                session, hs, tied_parquet, name, partition_first, lineage=True
            )
            rng = np.random.default_rng(5)
            extra = pa.table(
                {
                    "k": pa.array(rng.integers(0, 3, 500), type=pa.int64()),
                    "s": pa.array(["dd"] * 500),
                    "v": pa.array(rng.normal(size=500)),
                }
            )
            extra_path = os.path.join(tied_parquet, f"extra-{name}.parquet")
            pq.write_table(extra, extra_path)
            session.index_manager.clear_cache()
            hs.refresh_index(name, C.REFRESH_MODE_INCREMENTAL)
            os.remove(extra_path)  # keep the source identical for the next leg
            session.index_manager.clear_cache()
            entry = session.index_manager.get_index_log_entry(name)
            return sorted(entry.content.files), files

        legacy, _ = run("rleg", False)
        pfirst, _ = run("rpf", True)
        # refresh MERGE appends new files next to the v0 ones; compare
        # only the refreshed version's files (same basenames both legs)
        _assert_identical_files(legacy, pfirst)


class TestPartitionedSortPermutation:
    @pytest.mark.parametrize(
        "n,nb,k",
        [(0, 8, 1), (1, 1, 1), (7, 3, 2), (50_000, 8, 1), (120_001, 200, 3)],
    )
    def test_matches_global_lexsort(self, n, nb, k):
        rng = np.random.default_rng(n + nb + k)
        reps = rng.integers(-(2**60), 2**60, size=(k, n), dtype=np.int64)
        buckets = rng.integers(0, nb, n).astype(np.int32)
        np.testing.assert_array_equal(
            partitioned_sort_permutation(reps, buckets, nb),
            sort_permutation(reps, buckets),
        )

    def test_heavy_ties_stability(self):
        rng = np.random.default_rng(9)
        n = 80_000
        reps = rng.integers(0, 2, size=(2, n), dtype=np.int64)
        buckets = rng.integers(0, 4, n).astype(np.int32)
        np.testing.assert_array_equal(
            partitioned_sort_permutation(reps, buckets, 4),
            sort_permutation(reps, buckets),
        )

    def test_single_and_empty_buckets(self):
        rng = np.random.default_rng(11)
        n = 10_000
        reps = rng.integers(-5, 5, size=(1, n), dtype=np.int64)
        # all rows in one bucket of many; most buckets empty
        buckets = np.full(n, 6, dtype=np.int32)
        np.testing.assert_array_equal(
            partitioned_sort_permutation(reps, buckets, 16),
            sort_permutation(reps, buckets),
        )


class TestPartitionByBucket:
    def test_twin_parity_and_offsets(self):
        rng = np.random.default_rng(3)
        for n, nb in [(0, 4), (1, 1), (999, 7), (200_000, 200)]:
            bids = rng.integers(0, nb, n).astype(np.int32)
            order, offsets = partition_by_bucket(bids, nb)
            np.testing.assert_array_equal(
                order, np.argsort(bids, kind="stable")
            )
            counts = np.bincount(bids, minlength=nb)
            np.testing.assert_array_equal(np.diff(offsets), counts)
            assert offsets[0] == 0 and offsets[-1] == n

    def test_numpy_twin_forced(self, monkeypatch):
        """With native disabled the twin must produce the identical
        partition."""
        from hyperspace_tpu import native

        rng = np.random.default_rng(4)
        bids = rng.integers(0, 8, 100_000).astype(np.int32)
        with_native = partition_by_bucket(bids, 8)
        monkeypatch.setattr(native, "partition_by_bucket_i32", lambda *a: None)
        without = partition_by_bucket(bids, 8)
        np.testing.assert_array_equal(with_native[0], without[0])
        np.testing.assert_array_equal(with_native[1], without[1])
