"""Chaos schedule harness (testing/chaos.py): randomized lifecycles
under injected crashes, asserting the full recovery contract — stable
log after recovery, serves bit-identical to a crash-free replica, zero
orphans after GC.

Tier-1 runs a short schedule with a few crash cells; the full
(lifecycle step × crash point) sweep is slow-marked and also runs — at
small scale — as the ``bench.py`` chaos rung that
``scripts/bench_smoke.sh`` gates on.
"""

import pytest

from hyperspace_tpu.testing import faults
from hyperspace_tpu.testing.chaos import (
    ChaosHarness,
    build_schedule,
    run_crash_matrix,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def test_schedule_is_deterministic_and_legal():
    a = build_schedule(7, 14)
    assert a == build_schedule(7, 14)
    assert a[0] == ("create",)
    # every refresh is immediately preceded by an append (cannot no-op)
    for i, step in enumerate(a):
        if step[0].startswith("refresh"):
            assert a[i - 1][0] == "append"


def test_clean_run_green(tmp_path):
    h = ChaosHarness(str(tmp_path), seed=1, n_steps=10)
    rep = h.run(run_name="clean")
    assert rep.serve_results, "schedule produced no serves"
    assert rep.stranded_after == 0
    assert rep.orphans_after_gc == 0
    assert rep.crashes_fired == 0


@pytest.mark.parametrize(
    ("cell", "point"),
    [
        (0, "after_begin_log"),     # crash the create
        (1, "mid_data_write"),      # crash a data-writing lifecycle op
        (1, "after_end_log"),       # committed-but-unpublished
    ],
)
def test_crash_cells_recover_and_match_replica(tmp_path, cell, point):
    h = ChaosHarness(str(tmp_path), seed=2, n_steps=10)
    clean = h.run(run_name="clean")
    rep = h.run(crash_step=cell, crash_point=point)
    assert rep.crashes_fired + rep.crashes_skipped == 1
    assert rep.stranded_after == 0
    assert rep.orphans_after_gc == 0
    assert len(rep.serve_results) == len(clean.serve_results)
    for got, want in zip(rep.serve_results, clean.serve_results):
        assert got.equals(want)


@pytest.mark.slow
def test_full_crash_matrix_slow(tmp_path):
    summary = run_crash_matrix(str(tmp_path), seed=5, n_steps=12)
    assert summary["cells"] > 0
    assert summary["crashes_fired"] >= summary["lifecycle_steps"]
    assert summary["stranded_after_recovery"] == 0
    assert summary["orphans_after_gc"] == 0
    assert summary["serve_mismatches"] == 0
    assert summary["serves_verified"] > 0
