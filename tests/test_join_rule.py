"""JoinIndexRule E2E + eligibility tests.

Mirrors ``covering/JoinIndexRuleTest.scala`` (eligibility filters) and the
join scenarios of ``E2EHyperspaceRulesTest`` (both sides rewritten, results
equal to the un-indexed run).
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig


@pytest.fixture
def hs(session):
    return Hyperspace(session)


@pytest.fixture
def join_tables(tmp_path):
    rng = np.random.default_rng(11)
    n1, n2 = 400, 600
    orders = pa.table(
        {
            "o_key": pa.array(rng.integers(0, 80, n1), type=pa.int64()),
            "o_amount": pa.array(rng.normal(100, 20, n1)),
            "o_tag": pa.array([f"t{int(x) % 4}" for x in rng.integers(0, 99, n1)]),
        }
    )
    items = pa.table(
        {
            "l_key": pa.array(rng.integers(0, 80, n2), type=pa.int64()),
            "l_qty": pa.array(rng.integers(1, 9, n2), type=pa.int64()),
        }
    )
    d1, d2 = tmp_path / "orders", tmp_path / "items"
    d1.mkdir(), d2.mkdir()
    for i in range(2):
        pq.write_table(orders.slice(i * 200, 200), d1 / f"p{i}.parquet")
    for i in range(3):
        pq.write_table(items.slice(i * 200, 200), d2 / f"p{i}.parquet")
    return str(d1), str(d2)


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


class TestJoinIndexRule:
    def _mk_indexes(self, session, hs, d1, d2):
        dfo = session.read.parquet(d1)
        dfi = session.read.parquet(d2)
        hs.create_index(dfo, CoveringIndexConfig("o_idx", ["o_key"], ["o_amount"]))
        hs.create_index(dfi, CoveringIndexConfig("l_idx", ["l_key"], ["l_qty"]))
        return dfo, dfi

    def test_join_rewritten_both_sides_and_matches(
        self, session, hs, join_tables
    ):
        d1, d2 = join_tables
        dfo, dfi = self._mk_indexes(session, hs, d1, d2)
        q = lambda o, i: (
            o.join(i, on=o["o_key"] == i["l_key"])
            .select("o_key", "o_amount", "l_qty")
        )
        session.disable_hyperspace()
        base = q(dfo, dfi).collect()
        session.enable_hyperspace()
        plan = q(dfo, dfi).explain()
        assert plan.count("Hyperspace(Type: CI") == 2, plan
        assert "o_idx" in plan and "l_idx" in plan
        got = q(dfo, dfi).collect()
        assert sorted_table(got).equals(sorted_table(base))
        assert got.num_rows > 0

    def test_join_with_filter_sides(self, session, hs, join_tables):
        d1, d2 = join_tables
        dfo, dfi = self._mk_indexes(session, hs, d1, d2)
        q = lambda o, i: (
            o.filter(o["o_key"] > 10)
            .join(i, on=o["o_key"] == i["l_key"])
            .select("o_key", "l_qty")
        )
        session.disable_hyperspace()
        base = q(dfo, dfi).collect()
        session.enable_hyperspace()
        plan = q(dfo, dfi).explain()
        assert plan.count("Hyperspace(Type: CI") == 2, plan
        got = q(dfo, dfi).collect()
        assert sorted_table(got).equals(sorted_table(base))

    def test_join_not_rewritten_when_columns_uncovered(
        self, session, hs, join_tables
    ):
        d1, d2 = join_tables
        dfo, dfi = self._mk_indexes(session, hs, d1, d2)
        session.enable_hyperspace()
        # o_tag is not covered by o_idx
        q = (
            dfo.join(dfi, on=dfo["o_key"] == dfi["l_key"])
            .select("o_key", "o_tag", "l_qty")
        )
        assert "Hyperspace" not in q.explain()

    def test_join_not_rewritten_when_index_on_wrong_column(
        self, session, hs, join_tables
    ):
        d1, d2 = join_tables
        dfo = session.read.parquet(d1)
        dfi = session.read.parquet(d2)
        # index on o_amount, join on o_key -> indexed != join cols
        hs.create_index(dfo, CoveringIndexConfig("o_bad", ["o_amount"], ["o_key"]))
        hs.create_index(dfi, CoveringIndexConfig("l_idx", ["l_key"], ["l_qty"]))
        session.enable_hyperspace()
        q = (
            dfo.join(dfi, on=dfo["o_key"] == dfi["l_key"])
            .select("o_key", "o_amount", "l_qty")
        )
        assert "Hyperspace" not in q.explain()

    def test_join_beats_filter_rule_on_score(self, session, hs, join_tables):
        """Join rewrite (70+70) must win over per-side filter rewrites."""
        d1, d2 = join_tables
        dfo, dfi = self._mk_indexes(session, hs, d1, d2)
        session.enable_hyperspace()
        q = (
            dfo.filter(dfo["o_key"] > 0)
            .join(dfi, on=dfo["o_key"] == dfi["l_key"])
            .select("o_key", "l_qty")
        )
        plan = q.explain()
        assert plan.count("Hyperspace(Type: CI") == 2

    def test_join_hybrid_appended_rows(self, session, hs, join_tables):
        d1, d2 = join_tables
        dfo, dfi = self._mk_indexes(session, hs, d1, d2)
        # append to the items side after indexing
        extra = pa.table(
            {
                "l_key": pa.array([5, 7, 7], type=pa.int64()),
                "l_qty": pa.array([100, 200, 300], type=pa.int64()),
            }
        )
        pq.write_table(extra, os.path.join(d2, "extra.parquet"))
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        session.index_manager.clear_cache()
        dfi2 = session.read.parquet(d2)
        q = lambda o, i: (
            o.join(i, on=o["o_key"] == i["l_key"]).select("o_key", "l_qty")
        )
        session.disable_hyperspace()
        base = q(dfo, dfi2).collect()
        session.enable_hyperspace()
        plan = q(dfo, dfi2).explain()
        assert plan.count("Hyperspace(Type: CI") == 2
        assert "Union" in plan
        got = q(dfo, dfi2).collect()
        assert sorted_table(got).equals(sorted_table(base))
        assert 300 in got.column("l_qty").to_pylist()

    def test_string_key_join_with_index(self, session, hs, tmp_path):
        a = pa.table(
            {"tag_a": ["x", "y", "z", "x", "w"], "va": [1, 2, 3, 4, 5]}
        )
        b = pa.table({"tag_b": ["x", "x", "q", "z"], "vb": [10, 20, 30, 40]})
        (tmp_path / "a").mkdir(), (tmp_path / "b").mkdir()
        pq.write_table(a, tmp_path / "a" / "p.parquet")
        pq.write_table(b, tmp_path / "b" / "p.parquet")
        dfa = session.read.parquet(str(tmp_path / "a"))
        dfb = session.read.parquet(str(tmp_path / "b"))
        hs.create_index(dfa, CoveringIndexConfig("a_idx", ["tag_a"], ["va"]))
        hs.create_index(dfb, CoveringIndexConfig("b_idx", ["tag_b"], ["vb"]))
        session.enable_hyperspace()
        q = dfa.join(dfb, on=dfa["tag_a"] == dfb["tag_b"]).select("va", "vb")
        plan = q.explain()
        assert plan.count("Hyperspace(Type: CI") == 2
        pairs = sorted(
            zip(q.collect().column("va").to_pylist(), q.collect().column("vb").to_pylist())
        )
        assert pairs == [(1, 10), (1, 20), (3, 40), (4, 10), (4, 20)]

    def test_join_with_lineage_does_not_leak_lineage_column(
        self, session, hs, join_tables
    ):
        """A lineage-enabled index replacing a bare-Scan join side must not
        surface _data_file_id in the join output (advisor round-1 high;
        reference CoveringIndexRuleUtils filters updatedOutput to the
        original relation attributes)."""
        d1, d2 = join_tables
        session.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        dfo = session.read.parquet(d1)
        dfi = session.read.parquet(d2)
        hs.create_index(
            dfo, CoveringIndexConfig("o_idx", ["o_key"], ["o_amount", "o_tag"])
        )
        hs.create_index(dfi, CoveringIndexConfig("l_idx", ["l_key"], ["l_qty"]))
        session.enable_hyperspace()
        # no select(): each side is a bare Scan, all columns used
        q = dfo.join(dfi, on=dfo["o_key"] == dfi["l_key"])
        plan = q.explain()
        assert plan.count("Hyperspace(Type: CI") == 2, plan
        got = q.collect()
        assert C.DATA_FILE_NAME_ID not in got.column_names
        assert set(got.column_names) == {
            "o_key", "o_amount", "o_tag", "l_key", "l_qty"
        }
        session.disable_hyperspace()
        base = q.collect()
        assert sorted_table(got).equals(sorted_table(base))

    def test_multi_key_join_with_nulls_device_path(self, session, hs, tmp_path):
        """Composite-key co-bucketed join through the device merge kernel,
        with null keys on both sides (SQL: null never matches)."""
        # force the device kernel path (default threshold would pick the
        # numpy twin at this size)
        session.conf.set(C.EXECUTION_DEVICE_JOIN_MIN_ROWS, 0)
        rng = np.random.default_rng(23)
        n1, n2 = 300, 500
        a = pa.table(
            {
                "k1": pa.array(
                    [None if i % 17 == 0 else int(x) for i, x in
                     enumerate(rng.integers(0, 12, n1))],
                    type=pa.int64(),
                ),
                "k2": pa.array(rng.integers(0, 5, n1), type=pa.int64()),
                "va": pa.array(rng.normal(size=n1)),
            }
        )
        b = pa.table(
            {
                "j1": pa.array(
                    [None if i % 13 == 0 else int(x) for i, x in
                     enumerate(rng.integers(0, 12, n2))],
                    type=pa.int64(),
                ),
                "j2": pa.array(rng.integers(0, 5, n2), type=pa.int64()),
                "vb": pa.array(rng.integers(0, 100, n2), type=pa.int64()),
            }
        )
        (tmp_path / "a").mkdir(), (tmp_path / "b").mkdir()
        pq.write_table(a, tmp_path / "a" / "p.parquet")
        pq.write_table(b, tmp_path / "b" / "p.parquet")
        dfa = session.read.parquet(str(tmp_path / "a"))
        dfb = session.read.parquet(str(tmp_path / "b"))
        hs.create_index(dfa, CoveringIndexConfig("ab_idx", ["k1", "k2"], ["va"]))
        hs.create_index(dfb, CoveringIndexConfig("bb_idx", ["j1", "j2"], ["vb"]))
        q = lambda l, r: (
            l.join(r, on=(l["k1"] == r["j1"]) & (l["k2"] == r["j2"]))
            .select("k1", "k2", "va", "vb")
        )
        session.disable_hyperspace()
        base = q(dfa, dfb).collect()
        session.enable_hyperspace()
        plan = q(dfa, dfb).explain()
        assert plan.count("Hyperspace(Type: CI") == 2, plan
        got = q(dfa, dfb).collect()
        assert sorted_table(got).equals(sorted_table(base))
        # pyarrow cross-check that nulls never joined
        assert not any(v is None for v in got.column("k1").to_pylist())

    def test_join_key_equal_to_pad_sentinel(self, session, hs):
        """A real INT64_MAX join key must not be dropped as kernel padding
        (positional validity under stable argsort, ops/join.py)."""
        from hyperspace_tpu.execution.join_exec import co_bucketed_join
        from hyperspace_tpu.io.columnar import ColumnarBatch

        MAX = (1 << 63) - 1
        l = ColumnarBatch.from_arrow(
            pa.table(
                {"k": pa.array([1, MAX, 5], type=pa.int64()), "a": [10, 20, 30]}
            )
        )
        r = ColumnarBatch.from_arrow(
            pa.table(
                {"j": pa.array([MAX, 5, MAX], type=pa.int64()), "b": [1, 2, 3]}
            )
        )
        out = co_bucketed_join({0: l}, {0: r}, [("k", "j")], None)
        rows = sorted(
            zip(out.column("k").values.tolist(), out.column("b").values.tolist())
        )
        assert rows == [(5, 2), (MAX, 1), (MAX, 3)]
