"""Concurrent serve frontend (serve/frontend.py, docs/serve-server.md).

Differential doctrine: every result a concurrent serve returns must be
bit-identical to what serial execution over the same source snapshot
returns — across single-flight dedup, load shedding, snapshot pinning,
and lifecycle actions (refresh/optimize/vacuum) racing the serves.
"""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import functions as hsf
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import ServeOverloadedError
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.serve import ServeFrontend, plan_fingerprint
from hyperspace_tpu.testing import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture
def s1(session_factory):
    return session_factory(1)


def _write_rows(path, n, seed, key_hi=400):
    rng = np.random.default_rng(seed)
    t = pa.table(
        {
            "k": pa.array(rng.integers(0, key_hi, n), pa.int64()),
            "q": pa.array(rng.integers(1, 50, n), pa.int64()),
        }
    )
    pq.write_table(t, path)


def _atomic_append(src_dir, tmp_dir, name, n, seed):
    """Publish a new source file atomically (write outside the listed
    dir, then rename in) so concurrent listings never see a torn file."""
    tmp = os.path.join(tmp_dir, name)
    _write_rows(tmp, n, seed)
    os.rename(tmp, os.path.join(src_dir, name))


@pytest.fixture
def indexed(s1, tmp_path):
    d = tmp_path / "src"
    d.mkdir()
    _write_rows(str(d / "p0.parquet"), 6000, 0)
    _write_rows(str(d / "p1.parquet"), 6000, 1)
    s1.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
    hs = Hyperspace(s1)
    df = s1.read.parquet(str(d))
    hs.create_index(df, CoveringIndexConfig("i1", ["k"], ["q"]))
    s1.enable_hyperspace()
    return s1, hs, df, str(d)


def _agg(df):
    return df.filter((df["k"] >= 50) & (df["k"] < 250)).agg(
        hsf.count().alias("n"), hsf.sum("q").alias("sq")
    )


class TestAdmission:
    def test_single_flight_dedup(self, indexed, monkeypatch):
        s, _hs, df, _d = indexed
        calls = []
        gate = threading.Event()
        from hyperspace_tpu import execution as X

        real_execute = X.execute

        def slow_execute(plan, session=None):
            calls.append(plan)
            gate.wait(10)
            return real_execute(plan, session)

        monkeypatch.setattr(X, "execute", slow_execute)
        fe = ServeFrontend(s)
        try:
            q = df.filter(df["k"] == 3).select("q")
            futs = [fe.submit(q) for _ in range(16)]
            assert len({id(f) for f in futs}) == 1  # one shared future
            gate.set()
            results = [f.result(30) for f in futs]
            assert len(calls) == 1  # ONE execution for 16 submits
            assert all(r.equals(results[0]) for r in results)
            assert fe.stats()["deduped"] == 15
            assert fe.stats()["admitted"] == 1
        finally:
            gate.set()
            fe.close()

    def test_distinct_plans_not_deduped(self, indexed):
        s, _hs, df, _d = indexed
        fe = ServeFrontend(s)
        try:
            a = fe.submit(df.filter(df["k"] == 3).select("q"))
            b = fe.submit(df.filter(df["k"] == 4).select("q"))
            assert a is not b
            a.result(30), b.result(30)
        finally:
            fe.close()

    def test_shedding_past_queue_depth(self, indexed):
        s, _hs, df, _d = indexed
        s.conf.set(C.SERVE_MAX_CONCURRENCY, 1)
        s.conf.set(C.SERVE_MAX_QUEUE_DEPTH, 1)
        gate = threading.Event()
        started = threading.Event()
        from hyperspace_tpu import execution as X

        real_execute = X.execute

        def slow_execute(plan, session=None):
            started.set()
            gate.wait(10)
            return real_execute(plan, session)

        fe = ServeFrontend(s)
        try:
            import unittest.mock as mock

            with mock.patch.object(X, "execute", slow_execute):
                qs = [
                    df.filter(df["k"] == i).select("q") for i in range(4)
                ]
                f0 = fe.submit(qs[0])
                assert started.wait(10)  # worker busy; queue empty
                f1 = fe.submit(qs[1])  # queued (depth 1 = full)
                with pytest.raises(ServeOverloadedError):
                    fe.submit(qs[2])
                assert fe.stats()["shed"] == 1
                gate.set()
                assert f0.result(30) is not None
                assert f1.result(30) is not None
        finally:
            gate.set()
            fe.close()
            s.conf.unset(C.SERVE_MAX_CONCURRENCY)
            s.conf.unset(C.SERVE_MAX_QUEUE_DEPTH)

    def test_closed_frontend_rejects(self, indexed):
        s, _hs, df, _d = indexed
        fe = ServeFrontend(s)
        fe.close()
        from hyperspace_tpu.exceptions import HyperspaceException

        with pytest.raises(HyperspaceException):
            fe.submit(df.filter(df["k"] == 1).select("q"))

    def test_plan_fingerprint_sees_file_snapshots(self, indexed, tmp_path):
        s, _hs, df, d = indexed
        q1 = df.filter(df["k"] == 3).select("q")
        fp1 = plan_fingerprint(q1.logical_plan)
        assert fp1 == plan_fingerprint(q1.logical_plan)
        _atomic_append(d, str(tmp_path), "p2.parquet", 100, 7)
        df2 = s.read.parquet(d)
        q2 = df2.filter(df2["k"] == 3).select("q")
        assert fp1 != plan_fingerprint(q2.logical_plan)


class TestConcurrentServes:
    def test_contended_serves_bit_identical(self, indexed):
        """8 client threads, mixed point/agg queries, serve cache on:
        every result equals the serial baseline."""
        s, _hs, df, _d = indexed
        s.conf.set(C.SERVE_CACHE_ENABLED, True)
        fe = ServeFrontend(s)
        try:
            keys = list(range(0, 64, 7))
            point = {
                k: s.execute(
                    df.filter(df["k"] == k).select("q").logical_plan
                )
                for k in keys
            }
            agg_base = s.execute(_agg(df).logical_plan)
            errors = []

            def client(i):
                try:
                    for j in range(6):
                        k = keys[(i + j) % len(keys)]
                        out = fe.serve(df.filter(df["k"] == k).select("q"))
                        assert out.equals(point[k])
                        out = fe.serve(_agg(df))
                        assert out.equals(agg_base)
                except Exception as exc:  # propagate to the main thread
                    errors.append(exc)

            threads = [
                threading.Thread(target=client, args=(i,)) for i in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
            assert not errors, errors
            st = fe.stats()
            assert st["failed"] == 0
            assert st["completed"] >= 1
        finally:
            fe.close()
            s.conf.set(C.SERVE_CACHE_ENABLED, False)
            s.clear_serve_cache()


class TestLifecycleWhileServing:
    """Refresh/optimize racing continuous serves: every result matches
    the serial result for the source snapshot that query saw — exactly
    one pinned index version, never a mix — and the index ends ACTIVE."""

    def _storm(self, s, hs, src_dir, scratch, actions, readers=3, iters=6):
        s.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        fe = ServeFrontend(s)
        results = []  # (files_tuple, pydict) per serve
        errors = []
        stop = threading.Event()

        def reader(i):
            try:
                for j in range(iters):
                    df = s.read.parquet(src_dir)
                    files = tuple(df.logical_plan.relation.files)
                    out = fe.serve(_agg(df))
                    results.append((files, out))
            except Exception as exc:
                errors.append(exc)

        def writer():
            try:
                for step, action in enumerate(actions):
                    action(step)
            except Exception as exc:
                errors.append(exc)
            finally:
                stop.set()

        threads = [
            threading.Thread(target=reader, args=(i,))
            for i in range(readers)
        ] + [threading.Thread(target=writer)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(180)
            assert not errors, errors
            assert fe.stats()["failed"] == 0
            # exactly-one-version check: per source snapshot, the serial
            # UNINDEXED result is the unique correct answer; a serve that
            # mixed two index versions could not reproduce it
            s.disable_hyperspace()
            try:
                expected = {}
                for files, out in results:
                    if files not in expected:
                        df = s.read.parquet(*files)
                        expected[files] = s.execute(_agg(df).logical_plan)
                    want = expected[files]
                    assert out.equals(want), (
                        out.to_pydict(),
                        want.to_pydict(),
                    )
            finally:
                s.enable_hyperspace()
            entry = s.index_manager.get_index_log_entry("i1")
            assert entry is not None and entry.state == States.ACTIVE
        finally:
            fe.close()
            s.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, False)

    def test_refresh_while_serving(self, indexed, tmp_path):
        s, hs, _df, d = indexed
        scratch = str(tmp_path / "scratch")
        os.makedirs(scratch, exist_ok=True)

        def step(i):
            _atomic_append(d, scratch, f"a{i}.parquet", 400, 100 + i)
            s.index_manager.clear_cache()
            hs.refresh_index("i1", "incremental")

        self._storm(s, hs, d, scratch, [step, step])

    def test_optimize_while_serving(self, indexed, tmp_path):
        s, hs, _df, d = indexed
        scratch = str(tmp_path / "scratch")
        os.makedirs(scratch, exist_ok=True)

        def append_refresh(i):
            _atomic_append(d, scratch, f"b{i}.parquet", 400, 200 + i)
            s.index_manager.clear_cache()
            hs.refresh_index("i1", "incremental")

        def optimize(_i):
            hs.optimize_index("i1", "quick")

        self._storm(s, hs, d, scratch, [append_refresh, optimize])

    def test_vacuum_while_serving_heals_by_repin(self, indexed, tmp_path):
        """vacuum(ACTIVE) deletes superseded version dirs while pinned
        queries may still hold them — the frontend's transient-I/O
        retry re-pins onto the surviving version."""
        s, hs, _df, d = indexed
        scratch = str(tmp_path / "scratch")
        os.makedirs(scratch, exist_ok=True)

        def refresh_then_vacuum(i):
            _atomic_append(d, scratch, f"c{i}.parquet", 400, 300 + i)
            s.index_manager.clear_cache()
            hs.refresh_index("i1", "incremental")
            hs.vacuum_index("i1")  # ACTIVE -> vacuum outdated versions

        self._storm(s, hs, d, scratch, [refresh_then_vacuum])
