"""Substrate tests: columnar batches, device hashing/sorting, mesh shuffle.

Distribution runs on the virtual 8-device CPU mesh from conftest — the
analogue of the reference testing Spark behavior on ``local[4]``
(``SparkInvolvedSuite.scala:31-47``).
"""

import numpy as np
import pyarrow as pa
import pytest

from hyperspace_tpu.io.columnar import Column, ColumnarBatch
from hyperspace_tpu.ops.hash import bucket_ids_np
from hyperspace_tpu.ops.sort import sort_permutation
from hyperspace_tpu.utils.hashing import murmur3_32_bytes, murmur3_64_bytes


class TestColumnar:
    def test_arrow_roundtrip_numeric_strings_nulls(self):
        t = pa.table(
            {
                "i": pa.array([1, 2, None, 4], type=pa.int64()),
                "f": pa.array([1.5, None, 3.0, 4.0], type=pa.float64()),
                "s": pa.array(["a", None, "a", "c"]),
                "b": pa.array([True, False, True, None]),
            }
        )
        rt = ColumnarBatch.from_arrow(t).to_arrow()
        assert rt.equals(t)

    def test_key_rep_stability_across_dictionaries(self):
        # Same values in different files (different dictionary orders) must
        # produce identical key reps — bucket layout depends on it.
        c1 = Column.from_arrow(pa.array(["x", "y", "z"]))
        c2 = Column.from_arrow(pa.array(["z", "x", "y", "x"]))
        r1 = {v: r for v, r in zip(["x", "y", "z"], c1.key_rep())}
        r2 = {v: r for v, r in zip(["z", "x", "y", "x"], c2.key_rep())}
        assert all(r1[k] == r2[k] for k in "xyz")

    def test_key_rep_floats_group_negzero_and_nan(self):
        c = Column.from_arrow(pa.array([0.0, -0.0, float("nan"), float("nan")]))
        r = c.key_rep()
        assert r[0] == r[1]
        assert r[2] == r[3]

    def test_concat_remaps_string_codes(self):
        a = Column.from_arrow(pa.array(["p", "q"]))
        b = Column.from_arrow(pa.array(["q", "r", None]))
        merged = Column.concat([a, b])
        assert merged.to_arrow().to_pylist() == ["p", "q", "q", "r", None]

    def test_nullable_int_key_rep_matches_non_nullable(self):
        # Nullable int columns must not decay to float64 — same value, same
        # key rep across files with and without nulls.
        a = Column.from_arrow(pa.array([1, 2, 3], type=pa.int64()))
        b = Column.from_arrow(pa.array([1, 2, None], type=pa.int64()))
        assert a.values.dtype == b.values.dtype == np.int64
        assert a.key_rep()[0] == b.key_rep()[0]

    def test_temporal_roundtrip_with_nulls(self):
        import datetime

        t = pa.table(
            {
                "d32": pa.array([datetime.date(2020, 1, 1), None], type=pa.date32()),
                "ts": pa.array(
                    [datetime.datetime(2020, 1, 1, 12), None],
                    type=pa.timestamp("us"),
                ),
            }
        )
        rt = ColumnarBatch.from_arrow(t).to_arrow()
        assert rt.equals(t)

    def test_dictionary_of_int_column(self):
        arr = pa.array([1, 2, 1, 3], type=pa.int64()).dictionary_encode()
        c = Column.from_arrow(arr)
        assert c.kind == "numeric"
        assert c.to_arrow().to_pylist() == [1, 2, 1, 3]

    def test_large_string_roundtrip(self):
        arr = pa.array(["a", "b"], type=pa.large_string())
        c = Column.from_arrow(arr)
        assert c.to_arrow().type == pa.large_string()

    def test_concat_empty_batches(self):
        t = pa.table({"k": pa.array([], type=pa.int64())})
        e = ColumnarBatch.from_arrow(t)
        out = ColumnarBatch.concat([e, e])
        assert out.num_rows == 0

    def test_take_and_filter(self):
        t = pa.table({"k": [10, 20, 30, 40], "s": ["a", "b", "c", "d"]})
        batch = ColumnarBatch.from_arrow(t)
        out = batch.filter(np.array([True, False, True, False])).to_arrow()
        assert out.column("k").to_pylist() == [10, 30]
        assert out.column("s").to_pylist() == ["a", "c"]


class TestHash:
    def test_murmur3_32_known_vectors(self):
        # Canonical murmur3-x86-32 test vectors.
        assert murmur3_32_bytes(b"", 0) == 0
        assert murmur3_32_bytes(b"", 1) == 0x514E28B7
        assert murmur3_32_bytes(b"hello", 0) == 0x248BFA47
        assert murmur3_32_bytes(b"hello, world", 0) == 0x149BBB7F

    def test_device_hash_matches_host_bytes_hash(self):
        # Device murmur3 over an int64 rep == host murmur3 over its 8 LE bytes.
        vals = np.array([0, 1, -1, 2**40 + 17, -(2**35)], dtype=np.int64)
        dev = bucket_ids_np(vals[None, :], 1 << 31, seed=7)
        host = np.array(
            [
                murmur3_32_bytes(int(v).to_bytes(8, "little", signed=True), 7)
                % (1 << 31)
                for v in vals
            ],
            dtype=np.int64,
        )
        np.testing.assert_array_equal(dev.astype(np.int64), host)

    def test_bucket_ids_deterministic_and_in_range(self):
        reps = np.random.default_rng(0).integers(-(2**62), 2**62, (2, 1000))
        b1 = bucket_ids_np(reps, 8)
        b2 = bucket_ids_np(reps, 8)
        np.testing.assert_array_equal(b1, b2)
        assert b1.min() >= 0 and b1.max() < 8
        # decently balanced
        counts = np.bincount(b1, minlength=8)
        assert counts.min() > 50

    def test_string_hash_64_stable(self):
        assert murmur3_64_bytes(b"abc") == murmur3_64_bytes(b"abc")
        assert murmur3_64_bytes(b"abc") != murmur3_64_bytes(b"abd")


class TestSort:
    def test_lexsort_primary_first(self):
        k0 = np.array([2, 1, 2, 1], dtype=np.int64)
        k1 = np.array([0, 3, 1, 2], dtype=np.int64)
        perm = sort_permutation(np.stack([k0, k1]))
        assert k0[perm].tolist() == [1, 1, 2, 2]
        assert k1[perm].tolist() == [2, 3, 0, 1]

    def test_bucket_grouping(self):
        bucket = np.array([3, 0, 3, 1], dtype=np.int32)
        keys = np.array([[9, 5, 1, 7]], dtype=np.int64)
        perm = sort_permutation(keys, bucket)
        assert bucket[perm].tolist() == [0, 1, 3, 3]
        assert keys[0][perm].tolist() == [5, 7, 1, 9]


class TestShuffle:
    def test_all_to_all_bucket_shuffle_preserves_rows(self):
        import jax

        from hyperspace_tpu.parallel import bucket_shuffle, default_mesh

        assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
        mesh = default_mesh()
        rng = np.random.default_rng(1)
        n, nb = 1003, 16  # deliberately not divisible by 8
        keys = rng.integers(0, 50, (1, n)).astype(np.int64)
        payload = rng.integers(0, 10**9, n).astype(np.int64)
        # pin the flat strategy: this test exercises the device
        # all_to_all itself (auto resolves a CPU mesh to the host-side
        # exchange; tests/test_exchange_strategies.py covers the matrix)
        buckets, (keys_out, payload_out) = bucket_shuffle(
            mesh, keys, [keys[0], payload], nb, strategy="flat"
        )
        # No rows lost or duplicated.
        assert len(buckets) == n
        np.testing.assert_array_equal(
            np.sort(payload_out), np.sort(payload)
        )
        # Bucket assignment matches the device hash.
        expected = bucket_ids_np(keys_out[None, :], nb)
        np.testing.assert_array_equal(buckets, expected)
        # Same key ⇒ same bucket (layout is a pure function of key values).
        same_key_same_bucket = {}
        for k, b in zip(keys_out, buckets):
            assert same_key_same_bucket.setdefault(int(k), int(b)) == int(b)

    def test_shuffle_key_payload_alignment(self):
        from hyperspace_tpu.parallel import bucket_shuffle, default_mesh

        mesh = default_mesh()
        n = 64
        keys = np.arange(n, dtype=np.int64)[None, :]
        payload = np.arange(n, dtype=np.int64) * 1000
        _, (k_out, p_out) = bucket_shuffle(
            mesh, keys, [keys[0], payload], 4, strategy="flat"
        )
        np.testing.assert_array_equal(k_out * 1000, p_out)


def test_bucket_ids_host_device_bit_exact():
    """The small-input host hash and the device kernel must agree
    bit-for-bit (build uses device at scale, pruning uses host)."""
    import numpy as np

    from hyperspace_tpu.ops import hash as H

    rng = np.random.default_rng(0)
    reps = rng.integers(-(2**62), 2**62, size=(2, 3000), dtype=np.int64)
    host = H.bucket_ids_np(reps, 16)
    assert len(host) == 3000
    # force the device path by lowering the threshold
    old = H._HOST_HASH_MAX_ROWS
    try:
        H._HOST_HASH_MAX_ROWS = 0
        dev = H.bucket_ids_np(reps, 16)
    finally:
        H._HOST_HASH_MAX_ROWS = old
    assert np.array_equal(host, dev)


def test_shuffle_cap_bounds_memory_and_preserves_rows():
    """The exchange buffer is sized to real traffic: skewed destinations
    still deliver every row, balanced data gets a cap near n_local/D (not
    n_local), and padding rows never inflate the cap."""
    import numpy as np

    from hyperspace_tpu.parallel.mesh import default_mesh
    from hyperspace_tpu.parallel.shuffle import _exchange_cap, bucket_shuffle

    mesh = default_mesh()
    D = mesh.devices.size
    rng = np.random.default_rng(3)
    n = 4096
    n_local = n // D
    valid = np.ones(n, dtype=bool)

    # skew: every row to one destination -> cap == n_local
    reps = np.zeros((1, n), dtype=np.int64)
    assert _exchange_cap(reps, valid, D * 4, D, 42) == n_local
    payload = np.arange(n, dtype=np.int64)
    buckets, cols = bucket_shuffle(
        mesh, reps, [reps[0], payload], D * 4, strategy="flat"
    )
    assert len(buckets) == n
    assert sorted(cols[1].tolist()) == list(range(n))

    # balanced: cap well below n_local (~n_local/D padded to pow2)
    reps = rng.integers(-(2**60), 2**60, size=(1, n), dtype=np.int64)
    cap = _exchange_cap(reps, valid, D * 4, D, 42)
    assert cap < n_local // 2, cap
    buckets, cols = bucket_shuffle(
        mesh, reps, [reps[0], payload], D * 4, strategy="flat"
    )
    assert len(buckets) == n
    assert sorted(cols[1].tolist()) == list(range(n))

    # padding rows (invalid) do not count toward the cap
    valid_half = valid.copy()
    valid_half[n // 2 :] = False
    reps_pad = reps.copy()
    reps_pad[:, n // 2 :] = 0  # pads all hash to one dest — must not matter
    cap_pad = _exchange_cap(reps_pad, valid_half, D * 4, D, 42)
    assert cap_pad < n_local // 2, cap_pad


class TestPallasHashKernel:
    def test_pallas_matches_host_twin(self):
        """The Pallas murmur3 kernel (interpret mode on CPU) is bit-exact
        against the numpy twin — same contract as the XLA kernel."""
        import numpy as np
        import jax.numpy as jnp

        from hyperspace_tpu.ops.hash import (
            _PALLAS_BLOCK_N,
            bucket_ids_host,
            bucket_ids_pallas,
            split_words_np,
        )

        rng = np.random.default_rng(3)
        n = _PALLAS_BLOCK_N
        reps = rng.integers(-(2**62), 2**62, (2, n)).astype(np.int64)
        out = np.asarray(bucket_ids_pallas(jnp.asarray(split_words_np(reps)), 8))
        assert np.array_equal(out, bucket_ids_host(reps, 8))
