"""MinMax layout analysis + quantile z-order.

Covers the reference's ``MinMaxAnalysisUtil`` behavior (per-file min/max
overlap analysis as the layout-quality metric) and uses it the way the
reference intends: to show that percentile-based z-order encoding
(``ZOrderField.scala:83+``) beats min/max encoding on skewed columns.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.plananalysis.minmax_analysis import (
    analyze_column,
    analyze_min_max,
    analyze_min_max_string,
)


class TestAnalyzeColumn:
    def test_disjoint_intervals_touch_one_file(self):
        res = analyze_column(
            "c", [(0, 9), (10, 19), (20, 29)], [100, 100, 100], 3, 300
        )
        assert res.max_files_per_lookup == 1
        assert res.max_bytes_per_lookup == 100

    def test_identical_intervals_touch_all(self):
        res = analyze_column("c", [(0, 10)] * 4, [50] * 4, 4, 200)
        assert res.max_files_per_lookup == 4
        assert res.max_bytes_per_lookup == 200

    def test_shared_endpoint_counts_both(self):
        # closed intervals: a lookup at 10 touches both files
        res = analyze_column("c", [(0, 10), (10, 20)], [1, 1], 2, 2)
        assert res.max_files_per_lookup == 2

    def test_all_null(self):
        res = analyze_column("c", [], [], 3, 300)
        assert res.min_val is None
        assert "null" in res.to_text()

    def test_nan_rows_do_not_poison_file_range(self, session, tmp_path):
        d = tmp_path / "nan"
        d.mkdir()
        pq.write_table(
            pa.table({"x": pa.array([1.0, 2.0, float("nan")])}),
            d / "a.parquet",
        )
        pq.write_table(
            pa.table({"x": pa.array([1.5, 3.0])}), d / "b.parquet"
        )
        df = session.read.parquet(str(d))
        (res,) = analyze_min_max(df, ["x"])
        # a lookup at 1.5 must count BOTH files (the NaN file really
        # contains 1.0..2.0); before the nan-aware range it reported a
        # [FLOAT_MAX, FLOAT_MAX] interval for file a
        assert res.max_files_per_lookup == 2
        assert res.min_val == 1.0 and res.max_val == 3.0


class TestAnalyzeDataFrame:
    def test_clustered_vs_random_layout(self, session, tmp_path):
        rng = np.random.default_rng(2)
        d = tmp_path / "lay"
        d.mkdir()
        vals = np.arange(4000)
        rand = rng.permutation(vals)
        for i in range(8):
            sl = slice(i * 500, (i + 1) * 500)
            pq.write_table(
                pa.table(
                    {
                        "clustered": pa.array(vals[sl], type=pa.int64()),
                        "random": pa.array(rand[sl], type=pa.int64()),
                        "name": pa.array([f"r{j}" for j in range(500)]),
                    }
                ),
                d / f"f{i}.parquet",
            )
        df = session.read.parquet(str(d))
        res = {r.column: r for r in analyze_min_max(df, ["clustered", "random"])}
        assert res["clustered"].max_files_per_lookup == 1
        assert res["random"].max_files_per_lookup == 8
        assert res["clustered"].avg_files_per_lookup < (
            res["random"].avg_files_per_lookup
        )
        text = analyze_min_max_string(df, ["clustered", "name"])
        assert "Max files for a point lookup: 1" in text
        assert "non-numeric" in text


@pytest.mark.parametrize("session", [8], indirect=True)
class TestQuantileZOrder:
    def _build_and_measure(self, session, tmp_path, src, quantile, name):
        from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig

        hs = Hyperspace(session)
        session.conf.set(C.ZORDER_QUANTILE_ENABLED, quantile)
        # small target so the z-sorted index splits into many files
        session.conf.set(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 8_000)
        df = session.read.parquet(src)
        hs.create_index(df, ZOrderCoveringIndexConfig(name, ["skewed", "uniform"]))
        entry = session.index_manager.get_index_log_entry(name)
        files = list(entry.content.files)
        assert len(files) > 4, "need a multi-file layout to measure"
        import os

        idx_df = session.read.parquet(os.path.dirname(files[0]))
        (res,) = analyze_min_max(idx_df, ["skewed"])
        return res

    def test_quantile_beats_minmax_on_skew(self, session, tmp_path):
        """99% of values live in [0, 1000); outliers reach 1e12. Min/max
        scaling collapses the dense region onto one z-word, so z-order
        degenerates and point lookups touch many files; quantile encoding
        keeps the address bits busy and lookups touch few."""
        rng = np.random.default_rng(7)
        d = tmp_path / "skew"
        d.mkdir()
        n = 8000
        dense = rng.integers(0, 1000, n, dtype=np.int64)
        outlier_at = rng.random(n) < 0.01
        skewed = np.where(
            outlier_at, rng.integers(1, 10**12, n, dtype=np.int64), dense
        )
        t = pa.table(
            {
                "skewed": pa.array(skewed, type=pa.int64()),
                "uniform": pa.array(
                    rng.integers(0, 10**6, n, dtype=np.int64)
                ),
            }
        )
        for i in range(4):
            pq.write_table(t.slice(i * (n // 4), n // 4), d / f"p{i}.parquet")

        mm = self._build_and_measure(session, tmp_path, str(d), False, "z_mm")
        qt = self._build_and_measure(session, tmp_path, str(d), True, "z_qt")
        # min/max scaling degenerates: every file spans the dense region,
        # so a point lookup there touches ALL files; quantile stays local.
        # (The per-bin avg is not comparable here: equal-width bins over
        # the outlier range hide the dense region, so assert on the exact
        # point-lookup maximum.)
        assert mm.max_files_per_lookup == mm.total_files
        assert qt.max_files_per_lookup < mm.max_files_per_lookup
