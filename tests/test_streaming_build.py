"""Streaming (>memory-budget) covering-index build — the wave loop.

The reference gets disk-backed shuffle from Spark
(covering/CoveringIndex.scala:58-61); here the build must bound peak
memory itself: waves within ``hyperspace.index.build.memoryBudgetBytes``,
per-bucket spill, per-bucket merge sort. These tests pin (a) the wave
planner, (b) that a budgeted build actually streams (multiple waves, no
full materialization), and (c) that the result is byte-equivalent in
content and layout to the in-memory build.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.indexes.covering_build import (
    SourceScan,
    estimated_materialized_bytes,
    plan_waves,
)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


@pytest.fixture
def wide_parquet(tmp_path):
    """8 files, ~64KB materialized each."""
    rng = np.random.default_rng(5)
    d = tmp_path / "wide"
    d.mkdir()
    for i in range(8):
        n = 4000
        t = pa.table(
            {
                "k": pa.array(rng.integers(0, 500, n), type=pa.int64()),
                "v": pa.array(rng.normal(size=n)),
            }
        )
        pq.write_table(t, d / f"part-{i}.parquet")
    return str(d)


class TestWavePlanner:
    def test_waves_respect_budget(self, wide_parquet):
        files = sorted(
            os.path.join(wide_parquet, f) for f in os.listdir(wide_parquet)
        )
        per_file = estimated_materialized_bytes(files[:1], "parquet")
        waves = plan_waves(files, "parquet", per_file * 3)
        assert len(waves) >= 3
        assert [f for w in waves for f in w] == files
        for w in waves[:-1]:
            assert len(w) <= 3

    def test_single_oversized_file_still_one_wave(self, wide_parquet):
        files = sorted(
            os.path.join(wide_parquet, f) for f in os.listdir(wide_parquet)
        )
        waves = plan_waves(files, "parquet", 1)  # every file over budget
        assert [len(w) for w in waves] == [1] * len(files)


class TestStreamingBuild:
    def _build(self, session, hs, src, name, budget):
        session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, budget)
        df = session.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig(name, ["k"], ["v"]))
        entry = session.index_manager.get_index_log_entry(name)
        return sorted(entry.content.files)

    def test_streamed_equals_in_memory_build(
        self, session, hs, wide_parquet, tmp_path
    ):
        files_mem = self._build(session, hs, wide_parquet, "mem", 0)
        per_file = estimated_materialized_bytes(
            [os.path.join(wide_parquet, os.listdir(wide_parquet)[0])], "parquet"
        )
        files_stream = self._build(
            session, hs, wide_parquet, "stream", int(per_file * 2.5)
        )
        assert len(files_mem) == len(files_stream)
        for fm, fs in zip(files_mem, files_stream):
            assert os.path.basename(fm) == os.path.basename(fs)
            tm, ts = pq.read_table(fm), pq.read_table(fs)
            # same rows; bucket files key-sorted in both layouts
            key = lambda t: t.sort_by([("k", "ascending"), ("v", "ascending")])
            assert key(tm).equals(key(ts))
            ks = ts.column("k").to_pylist()
            assert ks == sorted(ks)
        # no spill residue in the index tree
        index_dir = os.path.dirname(os.path.dirname(files_stream[0]))
        for root, dirs, _ in os.walk(index_dir):
            assert not [d for d in dirs if d.startswith("_spill_")]

    def test_streaming_never_materializes_more_than_wave(
        self, session, hs, wide_parquet, monkeypatch
    ):
        """The scan must be materialized wave-by-wave, never all files at
        once."""
        calls = []
        real = SourceScan.materialize

        def tracking(self, files=None):
            calls.append(len(files if files is not None else self.files))
            return real(self, files)

        monkeypatch.setattr(SourceScan, "materialize", tracking)
        per_file = estimated_materialized_bytes(
            [
                os.path.join(wide_parquet, sorted(os.listdir(wide_parquet))[0])
            ],
            "parquet",
        )
        self._build(session, hs, wide_parquet, "waves", int(per_file * 2.5))
        assert calls, "streaming build did not go through SourceScan"
        assert max(calls) <= 2  # budget 2.5 files -> at most 2 per wave
        assert len(calls) >= 4

    def test_streamed_index_serves_queries(self, session, hs, wide_parquet):
        per_file = estimated_materialized_bytes(
            [
                os.path.join(wide_parquet, sorted(os.listdir(wide_parquet))[0])
            ],
            "parquet",
        )
        self._build(session, hs, wide_parquet, "serveidx", int(per_file * 2.5))
        df = session.read.parquet(wide_parquet)
        q = lambda d: d.filter(d["k"] == 42).select("k", "v")
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        plan = q(df).explain()
        assert "Hyperspace(Type: CI, Name: serveidx" in plan
        got = q(df).collect()
        s = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        assert s(got).equals(s(base))

    @pytest.mark.parametrize("quantile", [False, True], ids=["minmax", "qt"])
    def test_zorder_streamed_equals_in_memory(
        self, session, hs, wide_parquet, quantile, monkeypatch
    ):
        """The two-pass streamed z-order build (stats -> z-range spill ->
        per-range merge) produces the SAME global row order as the
        in-memory build, wave by wave, and never materializes more than a
        wave (for min/max encoding, whose spec is sample-independent)."""
        import pyarrow.parquet as pq_

        from hyperspace_tpu.indexes.covering_build import SourceScan
        from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig

        session.conf.set(C.ZORDER_QUANTILE_ENABLED, quantile)
        session.conf.set(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 30_000)

        def build(name, budget):
            session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, budget)
            df = session.read.parquet(wide_parquet)
            hs.create_index(df, ZOrderCoveringIndexConfig(name, ["k"], ["v"]))
            entry = session.index_manager.get_index_log_entry(name)
            return sorted(entry.content.files)

        calls = []
        real = SourceScan.materialize

        def tracking(self, files=None):
            calls.append(len(files if files is not None else self.files))
            return real(self, files)

        monkeypatch.setattr(SourceScan, "materialize", tracking)
        files_mem = build("zmem", 0)
        assert not calls or max(calls) == 8  # in-memory: one full read
        calls.clear()
        from hyperspace_tpu.indexes.covering_build import (
            estimated_materialized_bytes,
        )

        per_file = estimated_materialized_bytes(
            [os.path.join(wide_parquet, sorted(os.listdir(wide_parquet))[0])],
            "parquet",
        )
        files_stream = build("zstr", int(per_file * 2.5))
        assert calls and max(calls) <= 2  # streamed: never > one wave
        rows_mem = [pq_.read_table(f).to_pydict() for f in files_mem]
        rows_str = [pq_.read_table(f).to_pydict() for f in files_stream]
        flat = lambda parts: [
            (k, v)
            for p in parts
            for k, v in zip(p["k"], p["v"])
        ]
        if quantile:
            # quantile specs differ (global stride sample vs per-wave
            # samples): same multiset of rows, both valid z-layouts
            assert sorted(flat(rows_mem)) == sorted(flat(rows_str))
        else:
            # min/max spec is identical -> identical GLOBAL order
            assert flat(rows_mem) == flat(rows_str)
        # spill cleaned up
        idx_dir = os.path.dirname(os.path.dirname(files_stream[0]))
        for _root, dirs, _f in os.walk(idx_dir):
            assert not [d for d in dirs if d.startswith("_spill_")]

    def test_zorder_streamed_string_keys_global_order(
        self, session, hs, tmp_path
    ):
        """String z-order keys must use a GLOBAL dictionary: wave-local
        ranks would interleave unrelated ranges. Streamed output must
        equal the in-memory build's global order."""
        import pyarrow.parquet as pq_

        from hyperspace_tpu.indexes.covering_build import (
            estimated_materialized_bytes,
        )
        from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig

        rng = np.random.default_rng(11)
        d = tmp_path / "zs"
        d.mkdir()
        # disjoint string ranges per file — the wave-local-rank failure mode
        for i, prefix in enumerate(["a", "k", "t", "z"]):
            t = pa.table(
                {
                    "s": pa.array(
                        [f"{prefix}{v:04d}" for v in rng.integers(0, 500, 2000)]
                    ),
                    "v": pa.array(rng.normal(size=2000)),
                }
            )
            pq_.write_table(t, d / f"f{i}.parquet")
        session.conf.set(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 20_000)

        def build(name, budget):
            session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, budget)
            df = session.read.parquet(str(d))
            hs.create_index(df, ZOrderCoveringIndexConfig(name, ["s"], ["v"]))
            entry = session.index_manager.get_index_log_entry(name)
            return sorted(entry.content.files)

        mem = build("zs_mem", 0)
        per_file = estimated_materialized_bytes(
            [str(d / "f0.parquet")], "parquet"
        )
        stream = build("zs_str", int(per_file * 1.5))
        seq = lambda files: [
            s for f in files for s in pq_.read_table(f).column("s").to_pylist()
        ]
        # single string key: z-order == lexicographic order, exactly equal
        assert seq(stream) == seq(mem)
        assert seq(stream) == sorted(seq(stream))

    def test_zorder_streamed_constant_key_bounded(self, session, hs, tmp_path):
        """A constant key funnels every row into one z-range; the merge
        must split/fall back instead of materializing the whole dataset."""
        import pyarrow.parquet as pq_

        from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig

        d = tmp_path / "zc"
        d.mkdir()
        for i in range(4):
            t = pa.table(
                {
                    "k": pa.array([7] * 2000, type=pa.int64()),
                    "v": pa.array(np.arange(2000)),
                }
            )
            pq_.write_table(t, d / f"f{i}.parquet")
        session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, 1)  # pathological
        session.conf.set(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 20_000)
        df = session.read.parquet(str(d))
        hs.create_index(df, ZOrderCoveringIndexConfig("zc", ["k"], ["v"]))
        entry = session.index_manager.get_index_log_entry("zc")
        total = sum(
            pq_.read_table(f).num_rows for f in entry.content.files
        )
        assert total == 8000

    def test_incremental_refresh_streams_appended(
        self, session, hs, wide_parquet
    ):
        session.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        files0 = self._build(session, hs, wide_parquet, "incr", 0)
        # append two more files, refresh incrementally under a tiny budget
        rng = np.random.default_rng(9)
        for i in range(2):
            t = pa.table(
                {
                    "k": pa.array(rng.integers(0, 500, 4000), type=pa.int64()),
                    "v": pa.array(rng.normal(size=4000)),
                }
            )
            pq.write_table(t, os.path.join(wide_parquet, f"extra-{i}.parquet"))
        session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, 1)
        session.index_manager.clear_cache()
        hs.refresh_index("incr", C.REFRESH_MODE_INCREMENTAL)
        session.index_manager.clear_cache()
        df = session.read.parquet(wide_parquet)
        q = lambda d: d.filter(d["k"] == 7).select("k", "v")
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        got = q(df).collect()
        s = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        assert s(got).equals(s(base))


class TestStreamingIncrementalRefresh:
    """Round-5: BOTH incremental-refresh inputs stream — appended source
    files and (for deletes) the previous index data via
    ``SourceScan.excluded_lineage_ids`` — for covering AND z-order."""

    def _track(self, monkeypatch):
        calls = []
        real = SourceScan.materialize

        def tracking(self, files=None):
            calls.append(len(files if files is not None else self.files))
            return real(self, files)

        monkeypatch.setattr(SourceScan, "materialize", tracking)
        return calls

    def _mk(self, session, hs, src, config):
        session.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        df = session.read.parquet(src)
        hs.create_index(df, config)

    def test_covering_delete_refresh_streams(
        self, session, hs, wide_parquet, monkeypatch
    ):
        self._mk(
            session, hs, wide_parquet,
            CoveringIndexConfig("cdel", ["k"], ["v"]),
        )
        victims = sorted(os.listdir(wide_parquet))[:2]
        for v in victims:
            os.remove(os.path.join(wide_parquet, v))
        calls = self._track(monkeypatch)
        session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, 1)
        session.index_manager.clear_cache()
        hs.refresh_index("cdel", C.REFRESH_MODE_INCREMENTAL)
        assert calls, "delete refresh bypassed the lazy scan"
        # budget of 1 byte: every wave is a single file — the previous
        # index data was never materialized whole
        assert max(calls) == 1
        session.index_manager.clear_cache()
        df = session.read.parquet(wide_parquet)
        q = lambda d: d.filter(d["k"] == 7).select("k", "v")
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        got = q(df).collect()
        s = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        assert s(got).equals(s(base))

    def test_zorder_incremental_refresh_streams(
        self, session, hs, wide_parquet, monkeypatch
    ):
        from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig

        self._mk(
            session, hs, wide_parquet,
            ZOrderCoveringIndexConfig("zincr", ["k"], ["v"]),
        )
        # append two files AND delete one: the refresh must stream the
        # appended source and the lineage-filtered previous index data
        rng = np.random.default_rng(11)
        for i in range(2):
            t = pa.table(
                {
                    "k": pa.array(rng.integers(0, 500, 4000), type=pa.int64()),
                    "v": pa.array(rng.normal(size=4000)),
                }
            )
            pq.write_table(
                t, os.path.join(wide_parquet, f"zextra-{i}.parquet")
            )
        victim = sorted(
            f for f in os.listdir(wide_parquet) if f.startswith("part-")
        )[0]
        os.remove(os.path.join(wide_parquet, victim))
        calls = self._track(monkeypatch)
        session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, 1)
        session.index_manager.clear_cache()
        hs.refresh_index("zincr", C.REFRESH_MODE_INCREMENTAL)
        assert calls, "z-order incremental refresh bypassed the lazy scan"
        assert max(calls) == 1  # bounded: one file per materialize call
        session.index_manager.clear_cache()
        df = session.read.parquet(wide_parquet)
        q = lambda d: d.filter((d["k"] >= 100) & (d["k"] < 140)).select("k", "v")
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        got = q(df).collect()
        s = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        assert s(got).equals(s(base))

    def test_composite_scan_preserves_order_and_columns(self, tmp_path):
        from hyperspace_tpu.indexes.covering_build import CompositeScan

        d = tmp_path / "cs"
        d.mkdir()
        pq.write_table(
            pa.table({"k": pa.array([1, 2], type=pa.int64()),
                      "v": pa.array([0.1, 0.2])}),
            str(d / "a.parquet"),
        )
        pq.write_table(
            pa.table({"k": pa.array([3], type=pa.int64()),
                      "v": pa.array([0.3])}),
            str(d / "b.parquet"),
        )
        s1 = SourceScan(
            files=(str(d / "a.parquet"),), fmt="parquet",
            columns=("k", "v"), file_ids=None, select_cols=("k", "v"),
        )
        s2 = SourceScan(
            files=(str(d / "b.parquet"),), fmt="parquet",
            columns=("k", "v"), file_ids=None, select_cols=("k", "v"),
        )
        cs = CompositeScan((s1, s2))
        assert cs.files == s1.files + s2.files
        full = cs.materialize()
        assert full.column("k").values.tolist() == [1, 2, 3]
        sub = cs.materialize([str(d / "b.parquet")])
        assert sub.column("k").values.tolist() == [3]
        stats = cs.stats_view(["k"])
        assert stats.materialize().column_names == ["k"]
