"""Streaming (>memory-budget) covering-index build — the wave loop.

The reference gets disk-backed shuffle from Spark
(covering/CoveringIndex.scala:58-61); here the build must bound peak
memory itself: waves within ``hyperspace.index.build.memoryBudgetBytes``,
per-bucket spill, per-bucket merge sort. These tests pin (a) the wave
planner, (b) that a budgeted build actually streams (multiple waves, no
full materialization), and (c) that the result is byte-equivalent in
content and layout to the in-memory build.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.indexes.covering_build import (
    SourceScan,
    estimated_materialized_bytes,
    plan_waves,
)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


@pytest.fixture
def wide_parquet(tmp_path):
    """8 files, ~64KB materialized each."""
    rng = np.random.default_rng(5)
    d = tmp_path / "wide"
    d.mkdir()
    for i in range(8):
        n = 4000
        t = pa.table(
            {
                "k": pa.array(rng.integers(0, 500, n), type=pa.int64()),
                "v": pa.array(rng.normal(size=n)),
            }
        )
        pq.write_table(t, d / f"part-{i}.parquet")
    return str(d)


class TestWavePlanner:
    def test_waves_respect_budget(self, wide_parquet):
        files = sorted(
            os.path.join(wide_parquet, f) for f in os.listdir(wide_parquet)
        )
        per_file = estimated_materialized_bytes(files[:1], "parquet")
        waves = plan_waves(files, "parquet", per_file * 3)
        assert len(waves) >= 3
        assert [f for w in waves for f in w] == files
        for w in waves[:-1]:
            assert len(w) <= 3

    def test_single_oversized_file_still_one_wave(self, wide_parquet):
        files = sorted(
            os.path.join(wide_parquet, f) for f in os.listdir(wide_parquet)
        )
        waves = plan_waves(files, "parquet", 1)  # every file over budget
        assert [len(w) for w in waves] == [1] * len(files)


class TestStreamingBuild:
    def _build(self, session, hs, src, name, budget):
        session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, budget)
        df = session.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig(name, ["k"], ["v"]))
        entry = session.index_manager.get_index_log_entry(name)
        return sorted(entry.content.files)

    def test_streamed_equals_in_memory_build(
        self, session, hs, wide_parquet, tmp_path
    ):
        files_mem = self._build(session, hs, wide_parquet, "mem", 0)
        per_file = estimated_materialized_bytes(
            [os.path.join(wide_parquet, os.listdir(wide_parquet)[0])], "parquet"
        )
        files_stream = self._build(
            session, hs, wide_parquet, "stream", int(per_file * 2.5)
        )
        assert len(files_mem) == len(files_stream)
        for fm, fs in zip(files_mem, files_stream):
            assert os.path.basename(fm) == os.path.basename(fs)
            tm, ts = pq.read_table(fm), pq.read_table(fs)
            # same rows; bucket files key-sorted in both layouts
            key = lambda t: t.sort_by([("k", "ascending"), ("v", "ascending")])
            assert key(tm).equals(key(ts))
            ks = ts.column("k").to_pylist()
            assert ks == sorted(ks)
        # no spill residue in the index tree
        index_dir = os.path.dirname(os.path.dirname(files_stream[0]))
        for root, dirs, _ in os.walk(index_dir):
            assert not [d for d in dirs if d.startswith("_spill_")]

    def test_streaming_never_materializes_more_than_wave(
        self, session, hs, wide_parquet, monkeypatch
    ):
        """The scan must be materialized wave-by-wave, never all files at
        once."""
        calls = []
        real = SourceScan.materialize

        def tracking(self, files=None):
            calls.append(len(files if files is not None else self.files))
            return real(self, files)

        monkeypatch.setattr(SourceScan, "materialize", tracking)
        per_file = estimated_materialized_bytes(
            [
                os.path.join(wide_parquet, sorted(os.listdir(wide_parquet))[0])
            ],
            "parquet",
        )
        self._build(session, hs, wide_parquet, "waves", int(per_file * 2.5))
        assert calls, "streaming build did not go through SourceScan"
        assert max(calls) <= 2  # budget 2.5 files -> at most 2 per wave
        assert len(calls) >= 4

    def test_streamed_index_serves_queries(self, session, hs, wide_parquet):
        per_file = estimated_materialized_bytes(
            [
                os.path.join(wide_parquet, sorted(os.listdir(wide_parquet))[0])
            ],
            "parquet",
        )
        self._build(session, hs, wide_parquet, "serveidx", int(per_file * 2.5))
        df = session.read.parquet(wide_parquet)
        q = lambda d: d.filter(d["k"] == 42).select("k", "v")
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        plan = q(df).explain()
        assert "Hyperspace(Type: CI, Name: serveidx" in plan
        got = q(df).collect()
        s = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        assert s(got).equals(s(base))

    def test_zorder_build_under_budget_materializes(
        self, session, hs, wide_parquet
    ):
        """Z-order's global sort is not streamed: a budget-exceeding build
        must materialize and succeed, not crash on the lazy scan."""
        from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig

        session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, 1)
        df = session.read.parquet(wide_parquet)
        hs.create_index(df, ZOrderCoveringIndexConfig("z1", ["k"], ["v"]))
        entry = session.index_manager.get_index_log_entry("z1")
        assert entry is not None and entry.content.files

    def test_incremental_refresh_streams_appended(
        self, session, hs, wide_parquet
    ):
        session.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        files0 = self._build(session, hs, wide_parquet, "incr", 0)
        # append two more files, refresh incrementally under a tiny budget
        rng = np.random.default_rng(9)
        for i in range(2):
            t = pa.table(
                {
                    "k": pa.array(rng.integers(0, 500, 4000), type=pa.int64()),
                    "v": pa.array(rng.normal(size=4000)),
                }
            )
            pq.write_table(t, os.path.join(wide_parquet, f"extra-{i}.parquet"))
        session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, 1)
        session.index_manager.clear_cache()
        hs.refresh_index("incr", C.REFRESH_MODE_INCREMENTAL)
        session.index_manager.clear_cache()
        df = session.read.parquet(wide_parquet)
        q = lambda d: d.filter(d["k"] == 7).select("k", "v")
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        got = q(df).collect()
        s = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        assert s(got).equals(s(base))
