"""Fused serve-pipeline compiler: three-way differential property suite.

The contract under test (docs/serve-compiler.md,
execution/pipeline_compiler.py): for every supported
``Filter(→Project)→Aggregate`` subtree over a pruned index scan,
``fused ≡ interpreted`` BIT-IDENTICALLY (same rows, same order, same
float bit patterns, same validity presence), and the fused result agrees
with the unindexed scan up to float-sum reassociation (different row
order feeding the sum). The suite runs the three-way
(fusedpipeline on ≡ off ≡ unindexed) across the dtype matrix from
``tests/test_range_prune.py`` — over range-pruned (z-order) and
bucket-pruned (covering) scans — including NaN/null groups, empty
row-group survivors, dispatch-threshold fallbacks, and the flag-off
restore of the old path.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import functions as F
from hyperspace_tpu.execution import pipeline_compiler as PC
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes import zonemaps
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig


@pytest.fixture
def s1(session_factory):
    """Mesh-1 session: the fused pass is a host compute substitution
    with no mesh axis."""
    return session_factory(1)


@pytest.fixture(autouse=True)
def _force_fused_dispatch():
    """Dispatch the fused kernel at test sizes (the calibrated crossover
    would otherwise route tiny fixtures to the interpreted chain and the
    suite would silently test nothing)."""
    old = PC._NATIVE_FUSED_PIPELINE_MIN_ROWS
    PC._NATIVE_FUSED_PIPELINE_MIN_ROWS = 1
    try:
        yield
    finally:
        PC._NATIVE_FUSED_PIPELINE_MIN_ROWS = old


def _write_files(tmp_path, name, table, n_files=4):
    d = tmp_path / name
    d.mkdir()
    n = table.num_rows
    for i in range(n_files):
        lo, hi = i * n // n_files, (i + 1) * n // n_files
        pq.write_table(table.slice(lo, hi - lo), str(d / f"part{i}.parquet"))
    return str(d)


def _tables_bit_equal(a: pa.Table, b: pa.Table) -> None:
    """Exact equality including row order and float BIT patterns —
    arrow's ``.equals`` treats NaN != NaN, which would reject identical
    aggregate outputs over NaN-bearing groups."""
    assert a.schema.equals(b.schema), (a.schema, b.schema)
    assert a.num_rows == b.num_rows, (a.num_rows, b.num_rows)
    for name in a.column_names:
        ca = a.column(name).combine_chunks()
        cb = b.column(name).combine_chunks()
        assert ca.is_valid().equals(cb.is_valid()), name
        if pa.types.is_floating(ca.type):
            va = np.asarray(ca.fill_null(0.0)).view(np.int64)
            vb = np.asarray(cb.fill_null(0.0)).view(np.int64)
            np.testing.assert_array_equal(va, vb, err_msg=name)
        else:
            assert ca.equals(cb), name


def _three_way(session, q, expect_fused=True):
    """q() with fusedpipeline on vs off (both index-served) vs the
    unindexed scan. on ≡ off bit-identically; vs raw the group keys and
    counts must agree exactly (float sums may reassociate across the
    different row order). Returns the fused-on table."""
    session.enable_hyperspace()
    zonemaps.invalidate_local_cache()
    PC.last_fused_stats = {}
    on = q()
    ran = PC.last_fused_stats.get("mode") == "agg"
    if expect_fused:
        assert ran, f"fused pipeline did not run: {PC.last_fused_stats}"
    session.conf.set(C.SERVE_FUSEDPIPELINE_ENABLED, False)
    PC.last_fused_stats = {}
    off = q()
    assert PC.last_fused_stats == {}, "fused ran with the flag off"
    session.conf.unset(C.SERVE_FUSEDPIPELINE_ENABLED)
    session.disable_hyperspace()
    raw = q()
    _tables_bit_equal(on, off)
    assert on.num_rows == raw.num_rows, (on.num_rows, raw.num_rows)
    return on


def _dtype_tables(rng, n=8000):
    """(name, arrays, cond_fn, agg_fn) — the range-prune dtype matrix
    extended with per-dtype aggregates (sum/min/max only where the fused
    set supports the type; strings keep count-only)."""
    base = np.datetime64("2019-01-01")
    days = np.sort(rng.integers(0, 900, n))

    def num_aggs(f):
        return (
            F.count().alias("n"),
            F.count("c").alias("nc"),
            F.min("c").alias("mn"),
            F.max("c").alias("mx"),
            F.sum("v").alias("sv"),
            F.avg("v").alias("av"),
        )

    def temporal_aggs(f):
        return (
            F.count().alias("n"),
            F.min("c").alias("mn"),
            F.max("c").alias("mx"),
            F.sum("v").alias("sv"),
        )

    def count_only(f):
        return (F.count().alias("n"), F.count("c").alias("nc"))

    v = rng.normal(0, 5, n)
    common = {
        "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        "v": pa.array(v),
    }
    yield "ints", {
        "c": pa.array(np.sort(rng.integers(-1000, 1000, n)), type=pa.int64()),
        **common,
    }, lambda df: (df["c"] >= -100) & (df["c"] < 250), num_aggs
    f = rng.normal(0, 100, n)
    f[::31] = np.nan
    yield "floats_nan", {
        "c": pa.array(f),
        **common,
    }, lambda df: (df["c"] > -50.0) & (df["c"] <= 50.0), num_aggs
    yield "strings", {
        "c": pa.array([f"k{int(x):06d}" for x in rng.integers(0, 5000, n)]),
        **common,
    }, lambda df: (df["p"] >= 2) & (df["p"] < 7), count_only
    yield "dates", {
        "c": pa.array((base + days).astype("datetime64[D]")),
        **common,
    }, lambda df: (
        (df["c"] >= np.datetime64("2019-06-01"))
        & (df["c"] <= np.datetime64("2019-09-01"))
    ), temporal_aggs
    yield "ts_tz", {
        "c": pa.array(
            (base + days).astype("datetime64[us]"),
            type=pa.timestamp("us", tz="UTC"),
        ),
        **common,
    }, lambda df: (df["c"] >= "2019-06-01") & (df["c"] < "2019-09-01"), (
        temporal_aggs
    )
    yield "nullable_int", {
        "c": pa.array(
            [
                None if i % 11 == 0 else int(x)
                for i, x in enumerate(np.sort(rng.integers(0, 10_000, n)))
            ],
            type=pa.int64(),
        ),
        **common,
    }, lambda df: (df["c"] > 2000) & (df["c"] <= 4000), num_aggs


class TestRangePrunedAggregateMatrix:
    """Aggregate over a RANGE-PRUNED (z-order) scan: pruned ≡ unpruned ≡
    fused across the dtype matrix. The z index narrows files/row groups
    before the fused pass consumes the survivors."""

    def test_dtype_matrix_grouped(self, s1, tmp_path):
        hs = Hyperspace(s1)
        rng = np.random.default_rng(7)
        for name, arrays, cond_fn, agg_fn in _dtype_tables(rng):
            d = _write_files(tmp_path, name, pa.table(arrays))
            df = s1.read.parquet(d)
            # the strings row filters on p (string terms are outside the
            # fused set), so ITS index keys on p — the query must be
            # index-served for the fused pass to engage at all
            icols = ["p"] if name == "strings" else ["c"]
            inc = [c for c in ("c", "p", "v") if c not in icols]
            hs.create_index(
                df, ZOrderCoveringIndexConfig(f"z_{name}", icols, inc)
            )
            # string filter columns are outside the fused term set: the
            # "strings" row filters on p instead so the fused pass runs,
            # and the count-only aggs keep string c in play via COUNT(c)
            q = lambda: (
                df.filter(cond_fn(df))
                .group_by("p")
                .agg(*agg_fn(df))
                .collect()
            )
            out = _three_way(s1, q)
            assert 0 < out.num_rows <= 10, (name, out.num_rows)
            hs.delete_index(f"z_{name}")
            hs.vacuum_index(f"z_{name}")
            s1.index_manager.clear_cache()

    def test_nan_and_null_group_keys(self, s1, tmp_path):
        """Group keys with NaN payloads and NULLs: NaNs one group, nulls
        one group, both orderable — and the fused key column carries the
        FIRST-occurrence raw value exactly like take(first)."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(11)
        n = 6000
        g = rng.normal(0, 2, n).round(1)
        g[::13] = np.nan
        g[::17] = -0.0
        g[::19] = 0.0
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 5000, n)), type=pa.int64()),
            "g": pa.array(
                [None if i % 23 == 0 else float(x) for i, x in enumerate(g)],
                type=pa.float64(),
            ),
            "v": pa.array(rng.normal(0, 5, n)),
        }
        d = _write_files(tmp_path, "nanng", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(
            df, ZOrderCoveringIndexConfig("z_nn", ["c"], ["g", "v"])
        )
        q = lambda: (
            df.filter((df["c"] >= 500) & (df["c"] < 3500))
            .group_by("g")
            .agg(
                F.count().alias("n"),
                F.sum("v").alias("sv"),
                F.min("v").alias("mnv"),
                F.max("v").alias("mxv"),
            )
            .collect()
        )
        out = _three_way(s1, q)
        keys = out.column("g")
        assert keys.null_count == 1  # the null group
        assert any(
            v.as_py() is not None and np.isnan(v.as_py())
            for v in keys.combine_chunks()
            if v.is_valid
        )

    def test_empty_row_group_survivors(self, s1, tmp_path):
        """A range that prunes some files to EMPTY row-group tuples: the
        fused pass must stream zero-row chunks without disturbing the
        carried state, and an all-pruned predicate must yield the same
        empty/zero result as the interpreted chain."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(13)
        n = 8000
        arrays = {
            "c": pa.array(
                np.sort(rng.integers(0, 100_000, n)), type=pa.int64()
            ),
            "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
            "v": pa.array(rng.normal(0, 5, n)),
        }
        d = _write_files(tmp_path, "empties", pa.table(arrays))
        df = s1.read.parquet(d)
        s1.conf.set(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 16 * 1024)
        hs.create_index(
            df, ZOrderCoveringIndexConfig("z_e", ["c"], ["p", "v"])
        )
        s1.conf.unset(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION)
        # narrow range: most z files pruned, some survive
        q = lambda: (
            df.filter((df["c"] >= 10_000) & (df["c"] < 12_000))
            .group_by("p")
            .agg(F.count().alias("n"), F.sum("v").alias("sv"))
            .collect()
        )
        out = _three_way(s1, q)
        assert out.num_rows > 0
        # empty-range predicate: grouped result has zero rows, ungrouped
        # yields the one global row with count 0 — identical both paths
        qe = lambda: (
            df.filter((df["c"] >= 100_001) & (df["c"] < 100_002))
            .group_by("p")
            .agg(F.count().alias("n"))
            .collect()
        )
        oute = _three_way(s1, qe, expect_fused=False)
        assert oute.num_rows == 0
        qg = lambda: (
            df.filter((df["c"] >= 100_001) & (df["c"] < 100_002))
            .agg(F.count().alias("n"), F.sum("v").alias("sv"))
            .collect()
        )
        outg = _three_way(s1, qg, expect_fused=False)
        assert outg.column("n").to_pylist() == [0]
        assert outg.column("sv").to_pylist() == [None]


class TestBucketPrunedAggregate:
    def test_bucket_pruned_grouped(self, s1, tmp_path):
        """Aggregate over a BUCKET-PRUNED covering-index scan: the
        point predicate drops bucket files, the fused pass consumes the
        surviving buckets."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(17)
        n = 6000
        arrays = {
            "k": pa.array(rng.integers(0, 50, n), type=pa.int64()),
            "p": pa.array(rng.integers(0, 5, n), type=pa.int64()),
            "v": pa.array(rng.normal(0, 5, n)),
        }
        d = _write_files(tmp_path, "bp", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(df, CoveringIndexConfig("ci_bp", ["k"], ["p", "v"]))
        s1.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
        try:
            q = lambda: (
                df.filter(df["k"] == 7)
                .group_by("p")
                .agg(
                    F.count().alias("n"),
                    F.sum("v").alias("sv"),
                    F.min("v").alias("mn"),
                    F.max("v").alias("mx"),
                )
                .collect()
            )
            out = _three_way(s1, q)
            assert out.num_rows > 0
        finally:
            s1.conf.unset(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC)


class TestDispatchAndFallback:
    def _mk(self, s1, tmp_path, name="disp"):
        hs = Hyperspace(s1)
        rng = np.random.default_rng(19)
        n = 5000
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 5000, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 8, n), type=pa.int64()),
            "v": pa.array(rng.normal(0, 5, n)),
        }
        d = _write_files(tmp_path, name, pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(
            df, ZOrderCoveringIndexConfig(f"z_{name}", ["c"], ["p", "v"])
        )
        q = lambda: (
            df.filter((df["c"] >= 1000) & (df["c"] < 3000))
            .group_by("p")
            .agg(F.count().alias("n"), F.sum("v").alias("sv"))
            .collect()
        )
        return q

    def test_below_threshold_falls_back(self, s1, tmp_path):
        """Below the calibrated crossover the interpreted chain runs —
        same result, no fused telemetry."""
        q = self._mk(s1, tmp_path)
        s1.enable_hyperspace()
        PC._NATIVE_FUSED_PIPELINE_MIN_ROWS = 1 << 30
        PC.last_fused_stats = {}
        small = q()
        assert PC.last_fused_stats.get("mode") != "agg"
        PC._NATIVE_FUSED_PIPELINE_MIN_ROWS = 1
        PC.last_fused_stats = {}
        fused = q()
        assert PC.last_fused_stats.get("mode") == "agg"
        s1.disable_hyperspace()
        _tables_bit_equal(small, fused)

    def test_unsupported_predicate_falls_back(self, s1, tmp_path):
        """OR / IN / string predicates are outside the fused term set:
        the interpreted chain serves them, results unchanged."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(23)
        n = 5000
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 5000, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 8, n), type=pa.int64()),
            "v": pa.array(rng.normal(0, 5, n)),
        }
        d = _write_files(tmp_path, "unsup", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(
            df, ZOrderCoveringIndexConfig("z_unsup", ["c"], ["p", "v"])
        )
        q = lambda: (
            df.filter((df["c"] < 100) | (df["c"] > 4000))
            .group_by("p")
            .agg(F.count().alias("n"))
            .collect()
        )
        out = _three_way(s1, q, expect_fused=False)
        assert out.num_rows > 0

    def test_serve_cache_fused_over_ram(self, s1, tmp_path):
        """Serve-server mode: the fused pass runs over the RAM-resident
        cached scan (chunks == 1, no parquet), the compiled lowering is
        a ("fusedplan", …) entry, and evict_kind reclaims it."""
        q = self._mk(s1, tmp_path, name="cache")
        s1.enable_hyperspace()
        s1.conf.set(C.SERVE_CACHE_ENABLED, True)
        try:
            cold = q()
            PC.last_fused_stats = {}
            warm = q()
            st = dict(PC.last_fused_stats)
            assert st.get("mode") == "agg" and st.get("chunks") == 1, st
            _tables_bit_equal(cold, warm)
            kinds = {k[0] for k in s1.serve_cache._entries}
            assert "fusedplan" in kinds, kinds
            assert s1.serve_cache.evict_kind("fusedplan") >= 1
        finally:
            s1.conf.set(C.SERVE_CACHE_ENABLED, False)
            s1.clear_serve_cache()
            s1.disable_hyperspace()


class TestFusedFilterProject:
    def test_filter_project_three_way(self, s1, tmp_path):
        """Plain Filter→Project over the index: the fused select kernel
        replaces mask + nonzero, output rows bit-identical including
        string columns carried through the projection."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(29)
        n = 6000
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 5000, n)), type=pa.int64()),
            "s": pa.array([f"v{int(x) % 97:03d}" for x in rng.integers(0, 10**6, n)]),
            "v": pa.array(rng.normal(0, 5, n)),
        }
        d = _write_files(tmp_path, "fp", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(
            df, ZOrderCoveringIndexConfig("z_fp", ["c"], ["s", "v"])
        )
        q = lambda: (
            df.filter((df["c"] >= 1000) & (df["c"] < 3000))
            .select("c", "s", "v")
            .collect()
        )
        s1.enable_hyperspace()
        zonemaps.invalidate_local_cache()
        PC.last_fused_stats = {}
        on = q()
        assert PC.last_fused_stats.get("mode") == "select", PC.last_fused_stats
        s1.conf.set(C.SERVE_FUSEDPIPELINE_ENABLED, False)
        off = q()
        s1.conf.unset(C.SERVE_FUSEDPIPELINE_ENABLED)
        s1.disable_hyperspace()
        raw = q()
        _tables_bit_equal(on, off)
        assert on.num_rows == raw.num_rows
