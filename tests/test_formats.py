"""Default-source formats (avro/csv/json/orc/parquet/text) + glob roots.

Reference: ``DefaultFileBasedSource.scala:76-85`` (the six formats from
conf) and ``DefaultFileBasedRelation.scala:159-187`` (globbed root
handling). Text follows Spark's shape: one string column named ``value``.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


class TestOrc:
    def test_read_index_serve(self, session, tmp_path):
        from pyarrow import orc as paorc

        rng = np.random.default_rng(3)
        d = tmp_path / "orcsrc"
        d.mkdir()
        for i in range(2):
            t = pa.table(
                {
                    "k": pa.array(rng.integers(0, 50, 300), type=pa.int64()),
                    "v": pa.array(rng.normal(size=300)),
                }
            )
            paorc.write_table(t, str(d / f"f{i}.orc"))
        df = session.read.orc(str(d))
        assert df.count() == 600
        hs = Hyperspace(session)
        hs.create_index(df, CoveringIndexConfig("oidx", ["k"], ["v"]))
        session.enable_hyperspace()
        q = lambda dd: dd.filter(dd["k"] == 7).select("k", "v")
        plan = q(df).explain()
        assert "Hyperspace(Type: CI, Name: oidx" in plan
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df).collect()).equals(sorted_table(base))


class TestText:
    def test_read_filter(self, session, tmp_path):
        d = tmp_path / "txt"
        d.mkdir()
        (d / "a.txt").write_text("alpha\nbeta\ngamma\n")
        (d / "b.txt").write_text("delta\nbeta\n")
        df = session.read.text(str(d))
        assert df.columns == ["value"]
        assert df.count() == 5
        out = df.filter(df["value"] == "beta").collect()
        assert out.num_rows == 2


class TestAvro:
    def test_read_filter(self, session, tmp_path):
        from hyperspace_tpu.utils.avro import write_avro

        d = tmp_path / "av"
        d.mkdir()
        schema = {
            "type": "record",
            "name": "row",
            "fields": [
                {"name": "k", "type": "long"},
                {"name": "s", "type": "string"},
            ],
        }
        write_avro(
            str(d / "a.avro"),
            schema,
            [{"k": i, "s": f"v{i % 3}"} for i in range(30)],
        )
        df = session.read.avro(str(d))
        assert df.count() == 30
        out = df.filter(df["s"] == "v1").collect()
        assert out.num_rows == 10

    def test_empty_avro_file_concats(self, session, tmp_path):
        """An empty container file has no values to infer types from; the
        embedded schema must drive the Arrow types so the multi-file
        concat still works."""
        from hyperspace_tpu.utils.avro import write_avro

        d = tmp_path / "av2"
        d.mkdir()
        schema = {
            "type": "record",
            "name": "row",
            "fields": [
                {"name": "k", "type": "long"},
                {"name": "s", "type": ["null", "string"]},
            ],
        }
        write_avro(str(d / "a.avro"), schema, [{"k": 1, "s": "x"}])
        write_avro(str(d / "b.avro"), schema, [])
        write_avro(str(d / "c.avro"), schema, [{"k": 2, "s": None}])
        df = session.read.avro(str(d))
        out = df.collect()
        assert out.num_rows == 2
        assert str(out.schema.field("k").type) == "int64"


class TestGlobRoots:
    def test_glob_read_and_refresh(self, session, tmp_path):
        d = tmp_path / "g"
        d.mkdir()
        rng = np.random.default_rng(1)
        for i in range(2):
            pq.write_table(
                pa.table(
                    {
                        "k": pa.array(rng.integers(0, 20, 100), pa.int64()),
                        "v": pa.array(rng.normal(size=100)),
                    }
                ),
                d / f"part-{i}.parquet",
            )
        # decoy NOT matching the pattern
        pq.write_table(
            pa.table(
                {
                    "k": pa.array([999] * 5, pa.int64()),
                    "v": pa.array([0.0] * 5),
                }
            ),
            d / "other.parquet",
        )
        pattern = str(d / "part-*.parquet")
        df = session.read.parquet(pattern)
        assert df.count() == 200  # decoy excluded
        hs = Hyperspace(session)
        session.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        hs.create_index(df, CoveringIndexConfig("gidx", ["k"], ["v"]))
        entry = session.index_manager.get_index_log_entry("gidx")
        assert entry.relation.root_paths == [pattern]
        # append a file MATCHING the pattern; refresh must pick it up
        pq.write_table(
            pa.table(
                {
                    "k": pa.array([5] * 7, pa.int64()),
                    "v": pa.array([1.0] * 7),
                }
            ),
            d / "part-9.parquet",
        )
        hs.refresh_index("gidx", C.REFRESH_MODE_INCREMENTAL)
        session.index_manager.clear_cache()
        df2 = session.read.parquet(pattern)
        session.enable_hyperspace()
        q = df2.filter(df2["k"] == 5).select("k", "v")
        assert "Hyperspace(Type: CI, Name: gidx" in q.explain()
        session.disable_hyperspace()
        base = q.collect()
        session.enable_hyperspace()
        got = q.collect()
        assert sorted_table(got).equals(sorted_table(base))
        assert got.num_rows >= 7
