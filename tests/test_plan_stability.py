"""TPC-H-mini plan-stability golden harness.

The reference checks in 103 TPC-DS queries and snapshots their simplified
physical plans, failing CI on any plan change
(``goldstandard/PlanStabilitySuite.scala:46-290``). Same idea here at
TPC-H-mini scale: a deterministic generated dataset, a fixed index
inventory, and golden *simplified optimized plans* (paths and log versions
normalized) checked into ``tests/goldstandard/``.

Regenerate after an intentional planner change with:

    HS_GENERATE_GOLDEN_FILES=1 python -m pytest tests/test_plan_stability.py

and review the diff like the reference's SPARK_GENERATE_GOLDEN_FILES flow.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import functions as F
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.indexes.dataskipping import DataSkippingIndexConfig
from hyperspace_tpu.indexes.sketches import MinMaxSketch

from golden_utils import check_or_generate, simplify_plan

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "goldstandard")


def _gen_tpch_mini(root):
    """Deterministic TPC-H-shaped tables (SF ~0.001)."""
    rng = np.random.default_rng(1994)
    n_l, n_o, n_c = 2000, 400, 100
    base = np.datetime64("1994-01-01")
    lineitem = pa.table(
        {
            "l_orderkey": pa.array(
                rng.integers(0, n_o, n_l), type=pa.int64()
            ),
            "l_quantity": pa.array(
                rng.integers(1, 51, n_l), type=pa.int64()
            ),
            "l_extendedprice": pa.array(rng.normal(30000, 8000, n_l)),
            "l_shipdate": pa.array(
                (base + rng.integers(0, 1200, n_l).astype("timedelta64[D]"))
                .astype("datetime64[D]")
            ),
        }
    )
    orders = pa.table(
        {
            "o_orderkey": pa.array(np.arange(n_o), type=pa.int64()),
            "o_custkey": pa.array(
                rng.integers(0, n_c, n_o), type=pa.int64()
            ),
            "o_totalprice": pa.array(rng.normal(150000, 30000, n_o)),
        }
    )
    customer = pa.table(
        {
            "c_custkey": pa.array(np.arange(n_c), type=pa.int64()),
            "c_mktsegment": pa.array(
                [["BUILDING", "MACHINERY", "AUTOMOBILE"][i % 3] for i in range(n_c)]
            ),
        }
    )
    for name, table, parts in (
        ("lineitem", lineitem, 4),
        ("orders", orders, 2),
        ("customer", customer, 1),
    ):
        d = os.path.join(root, name)
        os.makedirs(d)
        rows = table.num_rows
        for i in range(parts):
            lo, hi = i * rows // parts, (i + 1) * rows // parts
            pq.write_table(table.slice(lo, hi - lo), os.path.join(d, f"part-{i}.parquet"))


@pytest.fixture
def tpch(session, tmp_path):
    root = str(tmp_path / "tpch")
    os.makedirs(root)
    _gen_tpch_mini(root)
    hs = Hyperspace(session)
    read = lambda t: session.read.parquet(os.path.join(root, t))
    li, od, cu = read("lineitem"), read("orders"), read("customer")
    hs.create_index(
        li,
        CoveringIndexConfig(
            "li_okey", ["l_orderkey"], ["l_quantity", "l_extendedprice"]
        ),
    )
    hs.create_index(od, CoveringIndexConfig("od_okey", ["o_orderkey"], ["o_custkey"]))
    hs.create_index(cu, CoveringIndexConfig("cu_ckey", ["c_custkey"], ["c_mktsegment"]))
    hs.create_index(
        li, DataSkippingIndexConfig("li_ship_sk", MinMaxSketch("l_shipdate"))
    )
    session.enable_hyperspace()
    session.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
    return {"lineitem": li, "orders": od, "customer": cu, "root": root}


def _queries(t):
    li, od, cu = t["lineitem"], t["orders"], t["customer"]
    return {
        # point filter on the covering index's first indexed column
        "q01_point_filter": li.filter(li["l_orderkey"] == 42).select(
            "l_orderkey", "l_quantity"
        ),
        # range filter served by the data-skipping sketch
        "q02_range_skip": li.filter(
            li["l_shipdate"] >= np.datetime64("1996-06-01")
        ).select("l_shipdate", "l_quantity"),
        # co-bucketed indexed join
        "q03_join": od.join(li, on=od["o_orderkey"] == li["l_orderkey"]).select(
            "o_orderkey", "o_custkey", "l_quantity"
        ),
        # join + filter + projection
        "q04_join_filter": od.join(
            li, on=od["o_orderkey"] == li["l_orderkey"]
        )
        .filter(od["o_custkey"] == 7)
        .select("o_orderkey", "l_extendedprice"),
        # aggregate over an index-served filter
        "q05_filter_agg": li.filter(li["l_orderkey"] == 42)
        .group_by("l_orderkey")
        .agg(F.sum("l_quantity").alias("qty")),
        # customer dimension join
        "q06_dim_join": cu.join(od, on=cu["c_custkey"] == od["o_custkey"]).select(
            "c_custkey", "c_mktsegment", "o_totalprice"
        ),
        # top-n
        "q07_topn": li.select("l_orderkey", "l_extendedprice")
        .sort(("l_extendedprice", False))
        .limit(5),
        # no index applies (predicate not on a first indexed column)
        "q08_no_index": li.filter(li["l_quantity"] == 1).select(
            "l_quantity", "l_extendedprice"
        ),
    }


def simplify(plan_str: str, root: str) -> str:
    return simplify_plan(plan_str, root)


QUERY_NAMES = [
    "q01_point_filter",
    "q02_range_skip",
    "q03_join",
    "q04_join_filter",
    "q05_filter_agg",
    "q06_dim_join",
    "q07_topn",
    "q08_no_index",
]


@pytest.mark.parametrize("qname", QUERY_NAMES)
def test_plan_stability(qname, session, tpch):
    queries = _queries(tpch)
    df = queries[qname]
    got = simplify(session.optimize(df.logical_plan).pretty(), tpch["root"])
    golden_path = os.path.join(GOLDEN_DIR, f"{qname}.txt")
    if check_or_generate(golden_path, got, qname):
        pytest.skip("golden file regenerated")
    # the plan must also EXECUTE and match the unindexed answer
    with_idx = df.collect()
    session.disable_hyperspace()
    base = df.collect()
    session.enable_hyperspace()
    key = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
    if qname == "q07_topn":
        # top-n with ties can pick different rows; compare the sort column
        assert with_idx.column("l_extendedprice").to_pylist() == (
            base.column("l_extendedprice").to_pylist()
        )
    else:
        assert key(with_idx).equals(key(base))
