"""Native C++ lexsort kernel: bit-exact parity with np.lexsort.

The contract (hyperspace_tpu/native/hs_native.cpp) is IDENTICAL output to
``np.lexsort(planes[::-1])`` — same stable tie order, not merely a valid
sort — because ``ops/sort.lexsort_perm`` relies on stability for the
pad-row trick and bucketed writes rely on deterministic run order.
"""

import numpy as np
import pytest

from hyperspace_tpu import native


pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native kernel unavailable (no g++?)"
)


def _check(planes):
    planes = np.ascontiguousarray(planes, dtype=np.uint32)
    got = native.lexsort_u32(planes)
    ref = np.lexsort(planes[::-1])
    np.testing.assert_array_equal(got, ref)


class TestLexsortParity:
    def test_empty_and_tiny(self):
        _check(np.zeros((3, 0), dtype=np.uint32))
        _check(np.array([[7]], dtype=np.uint32))
        _check(np.array([[2, 1], [9, 9]], dtype=np.uint32))

    def test_zero_planes(self):
        got = native.lexsort_u32(np.zeros((0, 5), dtype=np.uint32))
        np.testing.assert_array_equal(got, np.arange(5))

    @pytest.mark.parametrize("k", [1, 2, 3, 5])
    @pytest.mark.parametrize("n", [100, 4096, 100_003])
    def test_random(self, k, n):
        rng = np.random.default_rng(k * 1000 + n)
        _check(rng.integers(0, 2**32, size=(k, n), dtype=np.uint64))

    def test_heavy_ties_stability(self):
        # few distinct values -> long tie runs; stability is the contract
        rng = np.random.default_rng(7)
        _check(rng.integers(0, 4, size=(3, 50_000)))

    def test_constant_planes_skipped(self):
        # constant planes exercise the mask==0 short-circuit
        rng = np.random.default_rng(11)
        planes = np.stack(
            [
                np.full(10_000, 0x80000000, dtype=np.uint32),
                rng.integers(0, 100, 10_000).astype(np.uint32),
                np.zeros(10_000, dtype=np.uint32),
            ]
        )
        _check(planes)

    def test_all_constant(self):
        _check(np.full((4, 1000), 3, dtype=np.uint32))

    def test_single_active_byte_per_plane(self):
        # bucket-id-like plane (3 bits) + small-range low plane
        rng = np.random.default_rng(13)
        _check(
            np.stack(
                [
                    rng.integers(0, 8, 30_000).astype(np.uint32),
                    (rng.integers(0, 200, 30_000) << 16).astype(np.uint32),
                ]
            )
        )

    def test_extreme_values(self):
        vals = np.array(
            [0, 1, 0xFFFFFFFF, 0x80000000, 0x7FFFFFFF, 0xFF, 0xFF00],
            dtype=np.uint32,
        )
        rng = np.random.default_rng(17)
        _check(rng.choice(vals, size=(3, 10_000)))

    def test_bench_shape(self):
        # the covering-build shape: (bucket, hi^sign, lo) at real scale
        rng = np.random.default_rng(19)
        n = 500_000
        keys = rng.integers(-(2**40), 2**40, n).astype(np.int64)
        u = keys.view(np.uint64)
        planes = np.stack(
            [
                rng.integers(0, 8, n).astype(np.uint32),
                ((u >> np.uint64(32)).astype(np.uint32))
                ^ np.uint32(0x80000000),
                (u & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            ]
        )
        _check(planes)


def _merge_ref(ls, rs):
    """The numpy searchsorted+repeat expansion the kernel replaces."""
    if len(ls) == 0 or len(rs) == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    lo = np.searchsorted(rs, ls, side="left")
    hi = np.searchsorted(rs, ls, side="right")
    cnt = hi - lo
    total = int(cnt.sum())
    li = np.repeat(np.arange(len(ls), dtype=np.int64), cnt)
    starts = np.concatenate([[0], np.cumsum(cnt)[:-1]])
    within = np.arange(total, dtype=np.int64) - np.repeat(starts, cnt)
    return li, np.repeat(lo, cnt) + within


class TestMergeJoinParity:
    def _check(self, ls, rs):
        ls = np.sort(np.asarray(ls, dtype=np.int64))
        rs = np.sort(np.asarray(rs, dtype=np.int64))
        got = native.merge_join_i64(ls, rs)
        assert got is not None
        ref = _merge_ref(ls, rs)
        np.testing.assert_array_equal(got[0], ref[0])
        np.testing.assert_array_equal(got[1], ref[1])

    def test_empty_sides(self):
        self._check([], [])
        self._check([1, 2], [])
        self._check([], [1, 2])

    def test_no_overlap(self):
        self._check([1, 2, 3], [4, 5, 6])
        self._check([4, 5, 6], [1, 2, 3])

    def test_duplicates_both_sides(self):
        self._check([1, 1, 2, 2, 2, 3], [2, 2, 3, 3])

    def test_all_equal(self):
        self._check(np.zeros(100), np.zeros(50))

    def test_negative_and_extremes(self):
        vals = [-(2**62), -1, 0, 1, 2**62]
        rng = np.random.default_rng(3)
        self._check(rng.choice(vals, 1000), rng.choice(vals, 700))

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random(self, seed):
        rng = np.random.default_rng(seed)
        self._check(
            rng.integers(0, 10_000, 50_000), rng.integers(0, 10_000, 8_000)
        )


class TestBucketIdsParity:
    def _check(self, reps, num_buckets, seed=42):
        import hyperspace_tpu.ops.hash as hash_mod

        reps = np.asarray(reps, dtype=np.int64)
        got = native.bucket_ids_i64(reps, num_buckets, seed)
        assert got is not None
        # numpy twin, forced (bypass the native dispatch inside)
        words = hash_mod.split_words_np(reps)
        with np.errstate(over="ignore"):
            h = np.full(reps.shape[1], np.uint32(seed))
            for i in range(words.shape[0]):
                h = hash_mod._mix_h1(h, hash_mod._mix_k1(words[i]))
            h = hash_mod._fmix(h, np.uint32(4 * words.shape[0]))
        ref = (h % np.uint32(num_buckets)).astype(np.int32)
        np.testing.assert_array_equal(got, ref)

    @pytest.mark.parametrize("k", [1, 2, 3])
    def test_random(self, k):
        rng = np.random.default_rng(k)
        self._check(
            rng.integers(-(2**62), 2**62, size=(k, 40_000)), 8
        )

    def test_extremes_and_buckets(self):
        vals = np.array(
            [[-(2**63), 2**63 - 1, 0, -1, 1, 42]], dtype=np.int64
        )
        for nb in (1, 2, 7, 200, 65536):
            self._check(vals, nb)

    def test_dispatch_parity_end_to_end(self):
        """bucket_ids_host output is identical above/below the native
        threshold for the same values."""
        import hyperspace_tpu.ops.hash as hash_mod

        rng = np.random.default_rng(9)
        n = hash_mod._NATIVE_HASH_MIN_ROWS + 7
        reps = rng.integers(-(2**40), 2**40, size=(2, n))
        big = hash_mod.bucket_ids_host(reps, 16)
        small_parts = [
            hash_mod.bucket_ids_host(reps[:, i : i + 1000], 16)
            for i in range(0, n, 1000)
        ]
        np.testing.assert_array_equal(big, np.concatenate(small_parts))


class TestDispatch:
    def test_lexsort_perm_uses_native_above_threshold(self, monkeypatch):
        """lexsort_perm output is unchanged whichever engine runs."""
        from hyperspace_tpu.ops import sort as sort_mod

        rng = np.random.default_rng(23)
        n = sort_mod._NATIVE_SORT_MIN_ROWS + 10
        planes = rng.integers(0, 50, size=(2, n)).astype(np.uint32)
        native_perm = sort_mod.lexsort_perm(planes.copy())
        monkeypatch.setenv("HS_NATIVE", "0")
        # env var is read at load(); force a fresh decision
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", False)
        numpy_perm = sort_mod.lexsort_perm(planes.copy())
        np.testing.assert_array_equal(native_perm, numpy_perm)

    def test_fallback_when_unavailable(self, monkeypatch):
        monkeypatch.setenv("HS_NATIVE", "0")
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", False)
        assert native.load() is None
        assert native.lexsort_u32(np.zeros((1, 10), np.uint32)) is None
