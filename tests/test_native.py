"""Native C++ lexsort kernel: bit-exact parity with np.lexsort.

The contract (hyperspace_tpu/native/hs_native.cpp) is IDENTICAL output to
``np.lexsort(planes[::-1])`` — same stable tie order, not merely a valid
sort — because ``ops/sort.lexsort_perm`` relies on stability for the
pad-row trick and bucketed writes rely on deterministic run order.
"""

import os

import numpy as np
import pytest

from hyperspace_tpu import native


pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native kernel unavailable (no g++?)"
)


def _check(planes):
    planes = np.ascontiguousarray(planes, dtype=np.uint32)
    got = native.lexsort_u32(planes)
    ref = np.lexsort(planes[::-1])
    np.testing.assert_array_equal(got, ref)


class TestLexsortParity:
    def test_empty_and_tiny(self):
        _check(np.zeros((3, 0), dtype=np.uint32))
        _check(np.array([[7]], dtype=np.uint32))
        _check(np.array([[2, 1], [9, 9]], dtype=np.uint32))

    def test_zero_planes(self):
        got = native.lexsort_u32(np.zeros((0, 5), dtype=np.uint32))
        np.testing.assert_array_equal(got, np.arange(5))

    @pytest.mark.parametrize("k", [1, 2, 3, 5])
    @pytest.mark.parametrize("n", [100, 4096, 100_003])
    def test_random(self, k, n):
        rng = np.random.default_rng(k * 1000 + n)
        _check(rng.integers(0, 2**32, size=(k, n), dtype=np.uint64))

    def test_heavy_ties_stability(self):
        # few distinct values -> long tie runs; stability is the contract
        rng = np.random.default_rng(7)
        _check(rng.integers(0, 4, size=(3, 50_000)))

    def test_constant_planes_skipped(self):
        # constant planes exercise the mask==0 short-circuit
        rng = np.random.default_rng(11)
        planes = np.stack(
            [
                np.full(10_000, 0x80000000, dtype=np.uint32),
                rng.integers(0, 100, 10_000).astype(np.uint32),
                np.zeros(10_000, dtype=np.uint32),
            ]
        )
        _check(planes)

    def test_all_constant(self):
        _check(np.full((4, 1000), 3, dtype=np.uint32))

    def test_single_active_byte_per_plane(self):
        # bucket-id-like plane (3 bits) + small-range low plane
        rng = np.random.default_rng(13)
        _check(
            np.stack(
                [
                    rng.integers(0, 8, 30_000).astype(np.uint32),
                    (rng.integers(0, 200, 30_000) << 16).astype(np.uint32),
                ]
            )
        )

    def test_extreme_values(self):
        vals = np.array(
            [0, 1, 0xFFFFFFFF, 0x80000000, 0x7FFFFFFF, 0xFF, 0xFF00],
            dtype=np.uint32,
        )
        rng = np.random.default_rng(17)
        _check(rng.choice(vals, size=(3, 10_000)))

    def test_bench_shape(self):
        # the covering-build shape: (bucket, hi^sign, lo) at real scale
        rng = np.random.default_rng(19)
        n = 500_000
        keys = rng.integers(-(2**40), 2**40, n).astype(np.int64)
        u = keys.view(np.uint64)
        planes = np.stack(
            [
                rng.integers(0, 8, n).astype(np.uint32),
                ((u >> np.uint64(32)).astype(np.uint32))
                ^ np.uint32(0x80000000),
                (u & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            ]
        )
        _check(planes)


def _merge_ref(ls, rs):
    """The numpy searchsorted+repeat expansion the kernel replaces."""
    if len(ls) == 0 or len(rs) == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    lo = np.searchsorted(rs, ls, side="left")
    hi = np.searchsorted(rs, ls, side="right")
    cnt = hi - lo
    total = int(cnt.sum())
    li = np.repeat(np.arange(len(ls), dtype=np.int64), cnt)
    starts = np.concatenate([[0], np.cumsum(cnt)[:-1]])
    within = np.arange(total, dtype=np.int64) - np.repeat(starts, cnt)
    return li, np.repeat(lo, cnt) + within


class TestMergeJoinParity:
    def _check(self, ls, rs):
        ls = np.sort(np.asarray(ls, dtype=np.int64))
        rs = np.sort(np.asarray(rs, dtype=np.int64))
        got = native.merge_join_i64(ls, rs)
        assert got is not None
        ref = _merge_ref(ls, rs)
        np.testing.assert_array_equal(got[0], ref[0])
        np.testing.assert_array_equal(got[1], ref[1])

    def test_empty_sides(self):
        self._check([], [])
        self._check([1, 2], [])
        self._check([], [1, 2])

    def test_count_matches_reference(self):
        # hs_merge_join_count_i64 parity: the count pass must agree with
        # the searchsorted reference (and hence with the emit pass, whose
        # buffers are sized from it).
        rng = np.random.default_rng(19)
        for n, m in [(0, 7), (7, 0), (64, 64), (1000, 300)]:
            ls = np.sort(rng.integers(0, 50, n).astype(np.int64))
            rs = np.sort(rng.integers(0, 50, m).astype(np.int64))
            got = native.merge_join_count_i64(ls, rs)
            assert got is not None
            assert got == len(_merge_ref(ls, rs)[0])

    def test_no_overlap(self):
        self._check([1, 2, 3], [4, 5, 6])
        self._check([4, 5, 6], [1, 2, 3])

    def test_duplicates_both_sides(self):
        self._check([1, 1, 2, 2, 2, 3], [2, 2, 3, 3])

    def test_all_equal(self):
        self._check(np.zeros(100), np.zeros(50))

    def test_negative_and_extremes(self):
        vals = [-(2**62), -1, 0, 1, 2**62]
        rng = np.random.default_rng(3)
        self._check(rng.choice(vals, 1000), rng.choice(vals, 700))

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random(self, seed):
        rng = np.random.default_rng(seed)
        self._check(
            rng.integers(0, 10_000, 50_000), rng.integers(0, 10_000, 8_000)
        )


class TestPresortedFastPath:
    """_host_match's all-buckets-presorted fast path (count + biased
    emit-into) must return exactly what the per-bucket fallback loop
    returns — including the loff/roff bias plumbing."""

    def _preps(self, rng, n_left, n_right, n_buckets=4):
        from hyperspace_tpu.execution.join_exec import prepare_join_side
        from hyperspace_tpu.io.columnar import ColumnarBatch
        from hyperspace_tpu.ops.hash import bucket_ids_host
        from hyperspace_tpu.ops.sort import sort_permutation

        def side(n):
            keys = rng.integers(0, max(n // 4, 1), n).astype(np.int64)
            batches = {}
            reps = keys[None, :]
            bids = bucket_ids_host(reps, n_buckets)
            for b in range(n_buckets):
                idx = np.nonzero(bids == b)[0]
                if len(idx) == 0:
                    continue
                sub = keys[idx]
                perm = sort_permutation(sub[None, :])
                import pyarrow as pa

                batches[b] = ColumnarBatch.from_arrow(
                    pa.table({"k": sub[perm]})
                )
            return prepare_join_side(batches, ["k"])

        return side(n_left), side(n_right)

    def test_matches_fallback_loop(self, monkeypatch):
        from hyperspace_tpu.execution import join_exec as je

        pytest.importorskip("numpy")
        if __import__("hyperspace_tpu.native", fromlist=["load"]).load() is None:
            pytest.skip("native unavailable")
        rng = np.random.default_rng(31)
        lp, rp = self._preps(rng, 9000, 3000)
        assert lp.sorted_buckets and rp.sorted_buckets
        monkeypatch.setattr(je, "_NATIVE_JOIN_MIN_ROWS", 1)
        fast = je._host_match_native_presorted(
            lp, rp, lp.combined, rp.combined
        )
        assert fast is not None
        # force the fallback loop by making the fast path unavailable
        monkeypatch.setattr(
            je, "_host_match_native_presorted", lambda *a: None
        )
        slow = je._host_match(lp, rp, lp.combined, rp.combined)
        np.testing.assert_array_equal(fast[0], slow[0])
        np.testing.assert_array_equal(fast[1], slow[1])
        # the bias plumbing maps pairs to GLOBAL row ids: keys must match
        np.testing.assert_array_equal(
            lp.combined[fast[0]], rp.combined[fast[1]]
        )

    def test_empty_bucket_intersection(self, monkeypatch):
        from hyperspace_tpu.execution import join_exec as je

        if __import__("hyperspace_tpu.native", fromlist=["load"]).load() is None:
            pytest.skip("native unavailable")
        rng = np.random.default_rng(37)
        lp, rp = self._preps(rng, 600, 500)
        monkeypatch.setattr(je, "_NATIVE_JOIN_MIN_ROWS", 1)
        # disjoint key ranges -> zero pairs through the fast path
        lp2 = lp
        rp2 = rp
        shifted = rp.combined + np.int64(10**12)
        fast = je._host_match_native_presorted(lp2, rp2, lp.combined, shifted)
        assert fast is not None and len(fast[0]) == 0

    def test_unsorted_bucket_branch_matches_searchsorted(self, monkeypatch):
        """The in-loop native branch (argsorted buckets — multi-key or
        hybrid tails) must equal the numpy searchsorted expansion."""
        from hyperspace_tpu import native
        from hyperspace_tpu.execution import join_exec as je

        if native.load() is None:
            pytest.skip("native unavailable")
        import dataclasses

        rng = np.random.default_rng(43)
        lp, rp = self._preps(rng, 5000, 2000)
        # GENUINELY unsorted buckets: shuffle each bucket's combined-key
        # slice so the argsort inside _host_match is a real permutation
        # (sorted data would make it the identity and leave the perm
        # remap undiscriminated)
        combined = lp.combined.copy()
        for b in range(len(lp.sizes)):
            s, c = int(lp.offs[b]), int(lp.sizes[b])
            combined[s : s + c] = combined[s : s + c][rng.permutation(c)]
        lp = dataclasses.replace(
            lp, combined=combined, sorted_buckets=False
        )
        monkeypatch.setattr(je, "_NATIVE_JOIN_MIN_ROWS", 1)
        with_native = je._host_match(lp, rp, lp.combined, rp.combined)
        monkeypatch.setattr(native, "merge_join_i64", lambda *a: None)
        without = je._host_match(lp, rp, lp.combined, rp.combined)
        np.testing.assert_array_equal(with_native[0], without[0])
        np.testing.assert_array_equal(with_native[1], without[1])

    def test_emit_into_validates_outputs(self):
        from hyperspace_tpu import native

        if native.load() is None:
            pytest.skip("native unavailable")
        ls = np.array([1, 2, 3], dtype=np.int64)
        with pytest.raises(ValueError):
            native.merge_join_emit_into(
                ls, ls, np.empty(6, np.int32), np.empty(6, np.int64)
            )
        with pytest.raises(ValueError):
            native.merge_join_emit_into(
                ls, ls, np.empty(6, np.int64)[::2], np.empty(3, np.int64)
            )


class TestExpandMatchRangesParity:
    """hs_expand_match_ranges_i64 vs the numpy repeat/cumsum twin
    (ops/join.expand_match_ranges_numpy) — bit-exact, including the
    l_map/r_map indirections and biases the serve call sites use."""

    def _check(self, lo, cnt, l_map=None, r_map=None, l_bias=0, r_bias=0):
        from hyperspace_tpu.ops.join import expand_match_ranges_numpy

        lo = np.asarray(lo, dtype=np.int64)
        cnt = np.asarray(cnt, dtype=np.int64)
        total = int(cnt.sum())
        got = native.expand_match_ranges_i64(
            lo, cnt, total, l_map, r_map, l_bias, r_bias
        )
        assert got is not None
        ref = expand_match_ranges_numpy(lo, cnt, l_map, r_map, l_bias, r_bias)
        np.testing.assert_array_equal(got[0], ref[0])
        np.testing.assert_array_equal(got[1], ref[1])

    def test_empty(self):
        self._check([], [])

    def test_no_matches(self):
        self._check([0, 3, 5], [0, 0, 0])

    def test_all_match(self):
        # every left row matches the whole right side (cross-product
        # bucket): lo=0, cnt=m for all rows
        m = 37
        self._check(np.zeros(50, dtype=np.int64), np.full(50, m))

    def test_skewed_counts(self):
        # long zero runs around one huge range — the shape a skewed key
        # produces; exercises per-thread chunks with empty output slices
        cnt = np.zeros(10_000, dtype=np.int64)
        cnt[7_000] = 200_000
        lo = np.arange(10_000, dtype=np.int64)
        self._check(lo, cnt)

    def test_maps_and_biases(self):
        rng = np.random.default_rng(23)
        n = 50_000
        cnt = rng.integers(0, 4, n)
        lo = rng.integers(0, n, n)
        l_map = rng.permutation(n).astype(np.int64)
        r_map = rng.permutation(n + 4).astype(np.int64)
        self._check(lo, cnt, l_map, r_map, l_bias=1000, r_bias=-7)

    def test_negative_cnt_rejected(self):
        got = native.expand_match_ranges_i64(
            np.zeros(2, dtype=np.int64),
            np.array([1, -1], dtype=np.int64),
            0,
        )
        assert got is None

    def test_tiny_n_huge_counts_threaded(self):
        # few rows, pair count far above the threading threshold: the
        # ceil-chunking makes trailing thread chunks start past n, which
        # must be a no-op, not an out-of-bounds prefix-sum read
        self._check(np.zeros(5, dtype=np.int64), np.full(5, 60_000))

    def test_mismatched_total_rejected_before_writing(self):
        # the kernel re-validates capacity against its own prefix sum
        # BEFORE any write (a lying caller must not overrun li/ri)
        lo = np.zeros(3, dtype=np.int64)
        cnt = np.full(3, 10, dtype=np.int64)
        assert native.expand_match_ranges_i64(lo, cnt, 5) is None
        assert native.expand_match_ranges_i64(lo, cnt, 31) is None

    def test_short_maps_rejected(self):
        lo = np.array([0, 2], dtype=np.int64)
        cnt = np.array([2, 2], dtype=np.int64)
        short = np.zeros(3, dtype=np.int64)  # lo+cnt reaches 4
        assert (
            native.expand_match_ranges_i64(lo, cnt, 4, r_map=short) is None
        )
        assert (
            native.expand_match_ranges_i64(
                lo, cnt, 4, l_map=np.zeros(1, dtype=np.int64)
            )
            is None
        )

    def test_dispatch_native_off_leg(self, monkeypatch):
        """ops/join.expand_match_ranges output is identical with
        HS_NATIVE=0 (numpy twin leg) and with the kernel loaded."""
        from hyperspace_tpu.ops import join as join_mod

        rng = np.random.default_rng(29)
        n = 60_000
        cnt = rng.integers(0, 3, n).astype(np.int64)
        lo = rng.integers(0, n, n).astype(np.int64)
        monkeypatch.setattr(join_mod, "_NATIVE_EXPAND_MIN_ROWS", 1)
        with_native = join_mod.expand_match_ranges(lo, cnt)
        monkeypatch.setenv("HS_NATIVE", "0")
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", False)
        without = join_mod.expand_match_ranges(lo, cnt)
        np.testing.assert_array_equal(with_native[0], without[0])
        np.testing.assert_array_equal(with_native[1], without[1])


class TestGatherParity:
    """hs_gather_i64 / hs_gather_f64 vs numpy.take — bit-exact moves
    (NaN payloads survive the f64 leg), with out-of-range indices
    rejected so the Column.take dispatch preserves numpy semantics by
    falling back."""

    def test_i64_random(self):
        rng = np.random.default_rng(31)
        src = rng.integers(-(2**62), 2**62, 100_000, dtype=np.int64)
        idx = rng.integers(0, len(src), 250_000).astype(np.int64)
        np.testing.assert_array_equal(
            native.gather_i64(src, idx), np.take(src, idx)
        )

    def test_f64_random_with_nans(self):
        rng = np.random.default_rng(37)
        src = rng.normal(size=50_000)
        src[::97] = np.nan
        src[1::97] = -0.0
        idx = rng.integers(0, len(src), 120_000).astype(np.int64)
        got = native.gather_f64(src, idx)
        np.testing.assert_array_equal(
            got.view(np.int64), np.take(src, idx).view(np.int64)
        )

    def test_empty_idx(self):
        src = np.arange(10, dtype=np.int64)
        got = native.gather_i64(src, np.zeros(0, dtype=np.int64))
        assert got is not None and len(got) == 0

    def test_single_element_source(self):
        src = np.array([42], dtype=np.int64)
        idx = np.zeros(1000, dtype=np.int64)
        np.testing.assert_array_equal(native.gather_i64(src, idx), src[idx])

    def test_out_of_range_rejected(self):
        src = np.arange(100, dtype=np.int64)
        assert native.gather_i64(src, np.array([100], np.int64)) is None
        assert native.gather_i64(src, np.array([-1], np.int64)) is None
        assert native.gather_f64(src.astype(np.float64),
                                 np.array([-5], np.int64)) is None

    def test_column_take_dispatch_parity(self, monkeypatch):
        """Column.take output is identical above the native-gather
        threshold and with HS_NATIVE=0 — including negative indices,
        which the kernel rejects and numpy wraps."""
        from hyperspace_tpu.io import columnar as col_mod

        rng = np.random.default_rng(41)
        n = 80_000
        col = col_mod.Column(
            "numeric", __import__("pyarrow").int64(),
            values=rng.integers(-(2**40), 2**40, n),
        )
        idx = rng.integers(-n, n, 200_000).astype(np.int64)  # negatives wrap
        monkeypatch.setattr(col_mod, "_NATIVE_GATHER_MIN_ROWS", 1)
        with_native = col.take(idx).values
        monkeypatch.setenv("HS_NATIVE", "0")
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", False)
        without = col.take(idx).values
        np.testing.assert_array_equal(with_native, without)


class TestCleanupSupersededTTL:
    """Artifacts of OTHER source revisions sharing a cache dir survive
    until the age threshold — two checkouts must stop recompiling on
    every alternating process start (round-5 advisor finding)."""

    def test_young_foreign_artifact_survives(self, tmp_path):
        keep = str(tmp_path / "_hs_native_aaaa.so")
        young = tmp_path / "_hs_native_bbbb.so"
        young.write_bytes(b"x")
        stale = tmp_path / "_hs_native_cccc.so"
        stale.write_bytes(b"x")
        old = native._time.time() - 2 * native._SUPERSEDED_TTL_S
        os.utime(stale, (old, old))
        stale_failed = tmp_path / "_hs_native_dddd.so.failed"
        stale_failed.write_text("boom")
        os.utime(stale_failed, (old, old))
        stale_tmp = tmp_path / "_hs_native_eeee.so.tmp.123"
        stale_tmp.write_bytes(b"x")
        os.utime(stale_tmp, (old, old))
        young_tmp = tmp_path / "_hs_native_ffff.so.tmp.456"
        young_tmp.write_bytes(b"x")
        native._cleanup_superseded(keep)
        assert young.exists()  # another live checkout's kernel
        assert not stale.exists()  # genuinely abandoned revision
        assert not stale_failed.exists()
        # a week-old tmp is an orphan (SIGKILLed compile), not a compile
        # in progress — swept; a young tmp may be mid-compile — kept
        assert not stale_tmp.exists()
        assert young_tmp.exists()

    def test_load_refreshes_so_mtime(self, monkeypatch):
        """A revision that only ever LOADS its cached .so must keep a
        fresh mtime (the liveness signal the TTL gates on), or a sibling
        checkout reaps it after 7 days and the recompile ping-pong the
        TTL exists to stop comes back."""
        if native.load() is None:
            pytest.skip("native unavailable")
        path = native._cache_path()
        old = native._time.time() - 2 * native._SUPERSEDED_TTL_S
        os.utime(path, (old, old))
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", False)
        assert native.load() is not None
        age = native._time.time() - os.path.getmtime(path)
        assert age < native._SUPERSEDED_TTL_S / 2

    def test_own_artifacts_never_removed(self, tmp_path):
        keep = str(tmp_path / "_hs_native_aaaa.so")
        own = tmp_path / "_hs_native_aaaa.so"
        own.write_bytes(b"x")
        own_failed = tmp_path / "_hs_native_aaaa.so.failed"
        own_failed.write_text("boom")
        old = native._time.time() - 2 * native._SUPERSEDED_TTL_S
        for f in (own, own_failed):
            os.utime(f, (old, old))
        native._cleanup_superseded(keep)
        assert own.exists() and own_failed.exists()


class TestBucketIdsParity:
    def _check(self, reps, num_buckets, seed=42):
        import hyperspace_tpu.ops.hash as hash_mod

        reps = np.asarray(reps, dtype=np.int64)
        got = native.bucket_ids_i64(reps, num_buckets, seed)
        assert got is not None
        # numpy twin, forced (bypass the native dispatch inside)
        words = hash_mod.split_words_np(reps)
        with np.errstate(over="ignore"):
            h = np.full(reps.shape[1], np.uint32(seed))
            for i in range(words.shape[0]):
                h = hash_mod._mix_h1(h, hash_mod._mix_k1(words[i]))
            h = hash_mod._fmix(h, np.uint32(4 * words.shape[0]))
        ref = (h % np.uint32(num_buckets)).astype(np.int32)
        np.testing.assert_array_equal(got, ref)

    @pytest.mark.parametrize("k", [1, 2, 3])
    def test_random(self, k):
        rng = np.random.default_rng(k)
        self._check(
            rng.integers(-(2**62), 2**62, size=(k, 40_000)), 8
        )

    def test_extremes_and_buckets(self):
        vals = np.array(
            [[-(2**63), 2**63 - 1, 0, -1, 1, 42]], dtype=np.int64
        )
        for nb in (1, 2, 7, 200, 65536):
            self._check(vals, nb)

    def test_dispatch_parity_end_to_end(self):
        """bucket_ids_host output is identical above/below the native
        threshold for the same values."""
        import hyperspace_tpu.ops.hash as hash_mod

        rng = np.random.default_rng(9)
        n = hash_mod._NATIVE_HASH_MIN_ROWS + 7
        reps = rng.integers(-(2**40), 2**40, size=(2, n))
        big = hash_mod.bucket_ids_host(reps, 16)
        small_parts = [
            hash_mod.bucket_ids_host(reps[:, i : i + 1000], 16)
            for i in range(0, n, 1000)
        ]
        np.testing.assert_array_equal(big, np.concatenate(small_parts))


class TestPartitionKernelParity:
    """hs_partition_by_bucket vs the numpy twin (stable argsort +
    bincount prefix sum) — bit-exact, same offsets."""

    def _check(self, bids, nb):
        bids = np.ascontiguousarray(bids, dtype=np.int32)
        got = native.partition_by_bucket_i32(bids, nb)
        assert got is not None
        order, offsets = got
        np.testing.assert_array_equal(order, np.argsort(bids, kind="stable"))
        np.testing.assert_array_equal(
            np.diff(offsets), np.bincount(bids, minlength=nb)
        )
        assert offsets[0] == 0 and offsets[-1] == len(bids)

    def test_empty_and_tiny(self):
        self._check(np.zeros(0, dtype=np.int32), 4)
        self._check(np.array([0]), 1)
        self._check(np.array([2, 0, 2, 1]), 3)

    @pytest.mark.parametrize("nb", [1, 8, 200])
    @pytest.mark.parametrize("n", [100, 100_003, 1 << 18])
    def test_random(self, n, nb):
        rng = np.random.default_rng(n + nb)
        self._check(rng.integers(0, nb, n), nb)

    def test_skewed_single_bucket(self):
        # every row in one bucket: one cursor does all the writes
        self._check(np.full(50_000, 3, dtype=np.int32), 8)

    def test_out_of_range_ids_rejected(self):
        assert (
            native.partition_by_bucket_i32(np.array([0, 9], dtype=np.int32), 4)
            is None
        )
        assert (
            native.partition_by_bucket_i32(np.array([-1], dtype=np.int32), 4)
            is None
        )


class TestThreadScaling:
    def test_n_threads_scales_with_input(self):
        """Small inputs must not spawn a full thread complement
        (ADVICE round 5: 15 spawn/joins per byte pass at 33k rows)."""
        assert native._n_threads(0) == 1
        assert native._n_threads(1 << 15) == 1  # just above dispatch min
        assert native._n_threads(1 << 16) == 1
        assert native._n_threads(1 << 17) <= 2
        big = native._n_threads(1 << 30)
        assert big <= min(native._cores(), 16)


class TestFailedMarkerPolicy:
    def test_fresh_marker_honored_stale_removed(self, tmp_path):
        marker = str(tmp_path / "x.so.failed")
        with open(marker, "w") as f:
            f.write("boom")
        assert native._failed_marker_fresh(marker)
        # age it past the TTL: the marker is dropped and compile retried
        old = native._time.time() - 2 * native._FAILED_MARKER_TTL_S
        os.utime(marker, (old, old))
        assert not native._failed_marker_fresh(marker)
        assert not os.path.exists(marker)

    def test_missing_marker(self, tmp_path):
        assert not native._failed_marker_fresh(str(tmp_path / "none.failed"))

    def test_transient_compile_failure_writes_no_marker(
        self, tmp_path, monkeypatch
    ):
        """TimeoutExpired / OSError must not latch the machine-wide
        negative cache (one loaded-machine timeout would disable native
        kernels until an operator intervened)."""
        import subprocess as sp

        target = str(tmp_path / "k.so")

        def boom_timeout(*a, **k):
            raise sp.TimeoutExpired(cmd="g++", timeout=300)

        monkeypatch.setattr(native.subprocess, "run", boom_timeout)
        assert not native._compile(target)
        assert not os.path.exists(target + ".failed")

        def boom_compile(*a, **k):
            raise sp.CalledProcessError(1, "g++", stderr=b"syntax error")

        monkeypatch.setattr(native.subprocess, "run", boom_compile)
        assert not native._compile(target)
        assert os.path.exists(target + ".failed")

    def test_signal_killed_compiler_writes_no_marker(
        self, tmp_path, monkeypatch
    ):
        """g++ OOM-killed on a loaded machine (negative returncode) is
        transient: no marker, the next process retries."""
        import subprocess as sp

        target = str(tmp_path / "k.so")

        def boom_sigkill(*a, **k):
            raise sp.CalledProcessError(-9, "g++", stderr=b"")

        monkeypatch.setattr(native.subprocess, "run", boom_sigkill)
        assert not native._compile(target)
        assert not os.path.exists(target + ".failed")

    def test_missing_compiler_writes_marker(self, tmp_path, monkeypatch):
        """No g++ on PATH is deterministic, not transient: it earns the
        marker so a toolchain-less machine doesn't re-attempt the
        compile and warn in every process forever."""
        target = str(tmp_path / "k.so")

        def boom_missing(*a, **k):
            raise FileNotFoundError("g++: command not found")

        monkeypatch.setattr(native.subprocess, "run", boom_missing)
        assert not native._compile(target)
        assert os.path.exists(target + ".failed")


class TestCalibration:
    """Dispatch thresholds come from the cached per-machine probe; the
    ops constants are only the fallback (calibration disabled / no
    measurement / explicit override)."""

    @pytest.fixture(autouse=True)
    def fresh(self, tmp_path, monkeypatch):
        from hyperspace_tpu.native import calibrate

        monkeypatch.setattr(native, "_cache_dir", lambda: str(tmp_path))
        calibrate.invalidate()
        yield
        calibrate.invalidate()

    def test_probe_result_is_cached_to_disk(self, tmp_path, monkeypatch):
        import json

        from hyperspace_tpu.native import calibrate

        probed = calibrate.Thresholds(
            host_sort_max_rows=calibrate._NEVER,
            native_sort_min_rows=8192,
            host_hash_max_rows=calibrate._NEVER,
            native_hash_min_rows=4096,
            source="calibrated",
        )
        monkeypatch.setattr(calibrate, "_probe", lambda: probed)
        got = calibrate.thresholds()
        assert got.source == "calibrated"
        assert got.native_sort_min_rows == 8192
        with open(tmp_path / "_hs_calibration.json") as f:
            data = json.load(f)
        assert data["thresholds"]["native_sort_min_rows"] == 8192
        # a later process (fresh memo) reads the file, never re-probes
        calibrate.invalidate()
        monkeypatch.setattr(
            calibrate, "_probe", lambda: (_ for _ in ()).throw(AssertionError)
        )
        assert calibrate.thresholds().native_sort_min_rows == 8192

    def test_machine_key_mismatch_reprobes(self, monkeypatch):
        from hyperspace_tpu.native import calibrate

        monkeypatch.setattr(
            calibrate,
            "_probe",
            lambda: calibrate.Thresholds(
                native_sort_min_rows=1024, source="calibrated"
            ),
        )
        calibrate.thresholds()
        calibrate.invalidate()
        monkeypatch.setattr(
            calibrate, "_machine_key", lambda: {"version": -1, "cpus": 0}
        )
        monkeypatch.setattr(
            calibrate,
            "_probe",
            lambda: calibrate.Thresholds(
                native_sort_min_rows=2048, source="calibrated"
            ),
        )
        assert calibrate.thresholds().native_sort_min_rows == 2048

    def test_disabled_returns_defaults(self, monkeypatch):
        from hyperspace_tpu.native import calibrate

        monkeypatch.setenv("HS_CALIBRATE", "0")
        got = calibrate.thresholds()
        assert got.source == "defaults"
        assert got.native_sort_min_rows == 0  # 0 = use the ops constant

    def test_ops_fall_back_to_constants_when_disabled(self, monkeypatch):
        from hyperspace_tpu.native import calibrate
        from hyperspace_tpu.ops import hash as hash_mod
        from hyperspace_tpu.ops import sort as sort_mod

        monkeypatch.setenv("HS_CALIBRATE", "0")
        assert sort_mod._host_sort_max_rows() == sort_mod._HOST_SORT_MAX_ROWS
        assert (
            sort_mod._native_sort_min_rows()
            == sort_mod._NATIVE_SORT_MIN_ROWS
        )
        assert (
            sort_mod._native_partition_min_rows()
            == sort_mod._NATIVE_PARTITION_MIN_ROWS
        )
        assert hash_mod._host_hash_max_rows() == hash_mod._HOST_HASH_MAX_ROWS
        assert (
            hash_mod._native_hash_min_rows()
            == hash_mod._NATIVE_HASH_MIN_ROWS
        )

    def test_partition_threshold_calibrated(self, monkeypatch):
        """The counting-scatter kernel has its own measured crossover —
        it is not gated on the lexsort's (a different kernel with a
        different overhead profile)."""
        from hyperspace_tpu.native import calibrate
        from hyperspace_tpu.ops import sort as sort_mod

        monkeypatch.setattr(
            calibrate,
            "_probe",
            lambda: calibrate.Thresholds(
                native_partition_min_rows=1 << 17, source="calibrated"
            ),
        )
        assert sort_mod._native_partition_min_rows() == 1 << 17

    def test_probe_aborts_uncached_while_native_compiles(
        self, tmp_path, monkeypatch
    ):
        """A query thread probing while the warm thread holds the native
        build lock must get defaults immediately — no blocking behind
        the one-time g++ run, and no caching of the degraded result."""
        from hyperspace_tpu.native import calibrate

        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", False)
        monkeypatch.setattr(native, "load", lambda wait=True: None)
        got = calibrate.thresholds()
        assert got.source == "defaults"
        assert not os.path.exists(tmp_path / "_hs_calibration.json")
        assert calibrate.thresholds().source == "defaults"

    def test_module_attribute_override_beats_calibration(self, monkeypatch):
        from hyperspace_tpu.native import calibrate
        from hyperspace_tpu.ops import sort as sort_mod

        monkeypatch.setattr(
            calibrate,
            "_probe",
            lambda: calibrate.Thresholds(
                native_sort_min_rows=4096, source="calibrated"
            ),
        )
        monkeypatch.setattr(sort_mod, "_NATIVE_SORT_MIN_ROWS", 7)
        assert sort_mod._native_sort_min_rows() == 7

    def test_probe_failure_falls_back(self, monkeypatch):
        from hyperspace_tpu.native import calibrate

        monkeypatch.setattr(
            calibrate,
            "_probe",
            lambda: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        got = calibrate.thresholds()
        assert got.source == "defaults"


class TestDispatch:
    def test_lexsort_perm_uses_native_above_threshold(self, monkeypatch):
        """lexsort_perm output is unchanged whichever engine runs."""
        from hyperspace_tpu.ops import sort as sort_mod

        rng = np.random.default_rng(23)
        n = sort_mod._NATIVE_SORT_MIN_ROWS + 10
        planes = rng.integers(0, 50, size=(2, n)).astype(np.uint32)
        native_perm = sort_mod.lexsort_perm(planes.copy())
        monkeypatch.setenv("HS_NATIVE", "0")
        # env var is read at load(); force a fresh decision
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", False)
        numpy_perm = sort_mod.lexsort_perm(planes.copy())
        np.testing.assert_array_equal(native_perm, numpy_perm)

    def test_fallback_when_unavailable(self, monkeypatch):
        monkeypatch.setenv("HS_NATIVE", "0")
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", False)
        assert native.load() is None
        assert native.lexsort_u32(np.zeros((1, 10), np.uint32)) is None

    def test_missing_source_is_clean_fallback(self, monkeypatch):
        """A stripped install (no .cpp) must latch the numpy fallback,
        never raise out of load() into a query path."""
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", False)
        monkeypatch.setattr(native, "_SRC", "/nonexistent/hs_native.cpp")
        assert native.load() is None
        assert native._load_failed  # latched: no retry per call

    def test_concurrent_first_compile(self, tmp_path):
        """Several fresh processes racing the first-ever compile must all
        end up with a working kernel (atomic tmp+rename publish); the
        winner's .so is shared, losers' tmps vanish."""
        import os
        import shutil
        import subprocess
        import sys as _sys

        # load() can succeed via an already-cached .so; the children must
        # compile from scratch, so the compiler itself must exist
        if native.load() is None or shutil.which("g++") is None:
            pytest.skip("native toolchain unavailable")
        script = (
            "import sys, os\n"
            f"sys.path.insert(0, {repr(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))})\n"
            f"os.environ['XDG_CACHE_HOME'] = {repr(str(tmp_path))}\n"
            "import numpy as np\n"
            "from hyperspace_tpu import native\n"
            # force the user-cache dir so this test never touches the
            # repo's published .so
            "pkg = os.path.dirname(native._SRC)\n"
            "real = os.access\n"
            "os.access = lambda p, m: False if p == pkg else real(p, m)\n"
            "perm = native.lexsort_u32(\n"
            "    np.array([[3, 1, 2]], dtype=np.uint32))\n"
            "assert perm is not None and list(perm) == [1, 2, 0], perm\n"
            "print('ok')\n"
        )
        procs = [
            subprocess.Popen(
                [_sys.executable, "-c", script],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
            for _ in range(4)
        ]
        try:
            for p in procs:
                out, err = p.communicate(timeout=300)
                assert p.returncode == 0 and b"ok" in out, err[-500:]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        import glob as _glob

        sos = _glob.glob(str(tmp_path / "hyperspace_tpu" / "native" / "*.so"))
        tmps = _glob.glob(
            str(tmp_path / "hyperspace_tpu" / "native" / "*.tmp.*")
        )
        assert len(sos) == 1 and not tmps, (sos, tmps)

    def test_readonly_package_dir_uses_user_cache(
        self, monkeypatch, tmp_path
    ):
        """Read-only site-packages compiles into XDG_CACHE_HOME instead."""
        import os as _os

        monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
        pkg = _os.path.dirname(native._SRC)
        real_access = _os.access
        monkeypatch.setattr(
            _os,
            "access",
            lambda p, m: False if p == pkg else real_access(p, m),
        )
        path = native._cache_path()
        assert str(tmp_path) in path


class TestRangeMaskParity:
    """hs_range_mask vs ops/filter.range_mask_numpy (the registered
    KERNEL_TWINS reference) — the fused compare-AND of the range serve
    plane must match the per-conjunct numpy passes bit for bit,
    including NaN rows (fail every bound), validity masks, strict vs
    closed bounds and int64 extremes."""

    @staticmethod
    def _batch(n, seed=51, with_nulls=True):
        import pyarrow as pa

        from hyperspace_tpu.io.columnar import ColumnarBatch

        rng = np.random.default_rng(seed)
        f = rng.normal(0, 1, n)
        f[::13] = np.nan
        f[1::13] = -0.0
        cols = {
            "i": pa.array(
                rng.integers(-(2**62), 2**62, n, dtype=np.int64)
            ),
            "f": pa.array(f),
        }
        if with_nulls:
            cols["m"] = pa.array(
                [None if j % 7 == 0 else int(j) for j in range(n)],
                type=pa.int64(),
            )
        return ColumnarBatch.from_arrow(pa.table(cols))

    def _check(self, batch, terms):
        from hyperspace_tpu.ops.filter import range_mask_numpy

        ref = range_mask_numpy(batch, terms)
        cols, valids, is_f64, lo_i, hi_i, lo_f, hi_f, flags = (
            [], [], [], [], [], [], [], []
        )
        for name, lo, los, hi, his, empty in terms:
            assert not empty
            col = batch.columns[name]
            f64 = col.values.dtype.kind == "f"
            is_f64.append(f64)
            cols.append(col.values if f64 else col.values.view(np.int64))
            valids.append(col.validity)
            if f64:
                lo_f.append(float(lo) if lo is not None else 0.0)
                hi_f.append(float(hi) if hi is not None else 0.0)
                lo_i.append(0)
                hi_i.append(0)
            else:
                lo_i.append(int(lo) if lo is not None else 0)
                hi_i.append(int(hi) if hi is not None else 0)
                lo_f.append(0.0)
                hi_f.append(0.0)
            flags.append((lo is not None, hi is not None, los, his))
        got = native.range_mask_u8(
            cols, valids, is_f64, lo_i, hi_i, lo_f, hi_f, flags,
            batch.num_rows,
        )
        assert got is not None
        np.testing.assert_array_equal(got, ref)

    def test_int_bounds(self):
        batch = self._batch(100_000)
        self._check(batch, [("i", -(2**61), False, 2**61, True, False)])

    def test_float_bounds_nan_fails(self):
        batch = self._batch(100_000)
        self._check(batch, [("f", -0.5, True, 0.5, False, False)])

    def test_validity_and_multi_term(self):
        batch = self._batch(100_000)
        self._check(
            batch,
            [
                ("i", 0, False, None, False, False),
                ("f", None, False, 1.0, True, False),
                ("m", 100, True, 90_000, False, False),
            ],
        )

    def test_eq_as_closed_pair(self):
        batch = self._batch(50_000)
        v = int(batch.columns["i"].values[17])
        self._check(batch, [("i", v, False, v, False, False)])

    def test_int64_extremes(self):
        batch = self._batch(50_000)
        self._check(
            batch,
            [("i", -(2**63), False, 2**63 - 1, False, False)],
        )

    def test_float_bound_beyond_2_53_on_int64_matches_interpreter(self):
        """A float bound >= 2^53 on an int64 column must NOT take the
        exact-int native compare: the interpreter promotes the column to
        float64 (2^62+1 == 2^62 there), so the dispatch bails to the
        numpy twin, which replicates that promotion exactly."""
        import pyarrow as pa

        import hyperspace_tpu.ops.filter as F
        from hyperspace_tpu.io.columnar import ColumnarBatch
        from hyperspace_tpu.ops.filter import fused_range_mask
        from hyperspace_tpu.plan import expressions as E

        batch = ColumnarBatch.from_arrow(
            pa.table(
                {
                    "i": pa.array(
                        [2**62, 2**62 + 1, -(2**62) - 1, 0] * 10_000,
                        type=pa.int64(),
                    )
                }
            )
        )
        for cond in [
            E.Col("i") > float(2**62),
            E.Col("i") <= float(2**62),
            E.Col("i") >= -float(2**62),
        ]:
            ref = E.filter_mask(cond, batch)
            old = F._NATIVE_RANGE_MASK_MIN_ROWS
            try:
                F._NATIVE_RANGE_MASK_MIN_ROWS = 1
                got = fused_range_mask(cond, batch)
            finally:
                F._NATIVE_RANGE_MASK_MIN_ROWS = old
            assert got is not None
            np.testing.assert_array_equal(got, ref, err_msg=repr(cond))

    def test_fused_dispatch_matches_interpreter(self):
        """fused_range_mask (native leg forced) ≡ the expression
        interpreter's final mask on a supported conjunction."""
        import hyperspace_tpu.ops.filter as F
        from hyperspace_tpu.ops.filter import fused_range_mask
        from hyperspace_tpu.plan import expressions as E

        batch = self._batch(30_000)
        cond = (
            (E.Col("i") >= -(2**61))
            & (E.Col("f") > -0.25)
            & (E.Col("f") <= 0.25)
            & (E.Col("m") < 20_000)
        )
        ref = E.filter_mask(cond, batch)
        old = F._NATIVE_RANGE_MASK_MIN_ROWS
        try:
            F._NATIVE_RANGE_MASK_MIN_ROWS = 1
            got = fused_range_mask(cond, batch)
        finally:
            F._NATIVE_RANGE_MASK_MIN_ROWS = old
        assert got is not None
        np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# Fused serve-pipeline kernels (docs/serve-compiler.md): differential
# parity of hs_fused_filter_select / hs_fused_filter_agg against the
# registered INTERPRETED twins (pipeline_compiler.filter_select_interpreted
# / interpreted_filter_aggregate) — KERNEL_TWINS generalized from single
# kernels to whole pipelines, incl. float-sum accumulation order.
# ---------------------------------------------------------------------------


def _pc():
    from hyperspace_tpu.execution import pipeline_compiler as pc

    return pc


def _fused_batch(n, seed=0, with_nulls=True, float_key=False):
    import pyarrow as pa

    from hyperspace_tpu.io.columnar import Column, ColumnarBatch

    rng = np.random.default_rng(seed)
    k = rng.integers(0, max(n // 40, 3), n, dtype=np.int64)
    a = rng.integers(-100, 100, n, dtype=np.int64)
    b = rng.normal(0, 10, n)
    b[rng.random(n) < 0.03] = np.nan
    b[rng.random(n) < 0.01] = -0.0
    cols = {
        "k": Column(
            "numeric",
            pa.int64(),
            values=k,
            validity=(rng.random(n) > 0.05) if with_nulls else None,
        ),
        "a": Column("numeric", pa.int64(), values=a),
        "b": Column(
            "numeric",
            pa.float64(),
            values=b,
            validity=(rng.random(n) > 0.08) if with_nulls else None,
        ),
    }
    if float_key:
        fk = rng.normal(0, 1, n)
        fk[::17] = np.nan
        fk[::13] = -0.0
        fk[::11] = 0.0
        cols["fk"] = Column("numeric", pa.float64(), values=fk)
    schema = {nm: c.arrow_type for nm, c in cols.items()}
    return ColumnarBatch(cols), schema


def _assert_batches_bit_equal(a, b):
    """Bitwise batch equality: arrow's .equals treats NaN != NaN, so
    float columns compare by their int64 bit patterns after aligning
    validity — the right notion for the fused twin contract."""
    import pyarrow as pa

    ta, tb = a.to_arrow(), b.to_arrow()
    assert ta.schema.equals(tb.schema), (ta.schema, tb.schema)
    assert ta.num_rows == tb.num_rows, (ta.num_rows, tb.num_rows)
    for name in ta.column_names:
        ca = ta.column(name).combine_chunks()
        cb = tb.column(name).combine_chunks()
        assert ca.is_valid().equals(cb.is_valid()), name
        if pa.types.is_floating(ca.type):
            va = np.asarray(ca.fill_null(0.0)).view(np.int64)
            vb = np.asarray(cb.fill_null(0.0)).view(np.int64)
            np.testing.assert_array_equal(va, vb, err_msg=name)
        else:
            assert ca.equals(cb), name


_TERMS = (("a", -50, False, 70, True, False),)


def _all_agg_specs():
    from hyperspace_tpu.plan.nodes import AggSpec

    return [
        AggSpec("count", None, "n"),
        AggSpec("count", "b", "nb"),
        AggSpec("sum", "a", "sa"),
        AggSpec("sum", "b", "sb"),
        AggSpec("min", "a", "mna"),
        AggSpec("max", "a", "mxa"),
        AggSpec("min", "b", "mnb"),
        AggSpec("max", "b", "mxb"),
        AggSpec("avg", "b", "ab"),
    ]


class TestFusedFilterSelectParity:
    def _check(self, batch, terms):
        from hyperspace_tpu.ops import filter as F

        pc = _pc()
        prep = F.native_terms_for_batch(batch, terms)
        assert prep is not None and prep != F.NEVER_MATCH
        got = native.fused_filter_select(*prep, batch.num_rows)
        assert got is not None
        np.testing.assert_array_equal(
            got, pc.filter_select_interpreted(batch, terms)
        )

    @pytest.mark.parametrize("n", [1, 7, 1000, 100_000])
    def test_random(self, n):
        batch, _ = _fused_batch(n, seed=n)
        self._check(batch, _TERMS)

    def test_nulls_and_float_terms(self):
        batch, _ = _fused_batch(50_000, seed=3)
        self._check(
            batch,
            (
                ("a", -50, False, None, False, False),
                ("b", -5.0, True, 5.0, False, False),
            ),
        )

    def test_none_pass_and_all_pass(self):
        batch, _ = _fused_batch(10_000, seed=5, with_nulls=False)
        self._check(batch, (("a", 1000, False, None, False, False),))
        self._check(batch, (("a", None, False, 1000, False, False),))


class TestFusedFilterAggParity:
    def _check(self, batches, terms, group_by, aggs, schema):
        pc = _pc()
        if isinstance(batches, list):
            from hyperspace_tpu.io.columnar import ColumnarBatch

            whole = ColumnarBatch.concat(batches)
        else:
            whole = batches
        ref = pc.interpreted_filter_aggregate(
            whole, terms, group_by, aggs, schema
        )
        got = pc.kernel_filter_aggregate(batches, terms, group_by, aggs, schema)
        assert got is not None, "fused kernel path bailed"
        _assert_batches_bit_equal(ref, got)

    @pytest.mark.parametrize("n", [1, 37, 5000, 120_000])
    def test_grouped_all_ops(self, n):
        batch, schema = _fused_batch(n, seed=n)
        self._check(batch, _TERMS, ["k"], _all_agg_specs(), schema)

    def test_ungrouped_all_ops(self):
        batch, schema = _fused_batch(80_000, seed=11)
        self._check(batch, _TERMS, [], _all_agg_specs(), schema)

    def test_float_key_nan_negzero_groups(self):
        # NaN payloads collapse to one group, -0.0/0.0 group together,
        # and the FIRST-occurrence raw value is what the key column holds
        batch, schema = _fused_batch(60_000, seed=13, float_key=True)
        self._check(batch, _TERMS, ["fk"], _all_agg_specs(), schema)

    def test_multi_key_with_null_groups(self):
        batch, schema = _fused_batch(40_000, seed=17, float_key=True)
        self._check(batch, _TERMS, ["k", "fk"], _all_agg_specs(), schema)

    def test_chunked_equals_single_batch(self):
        # the executor streams row-group chunks through ONE carried
        # state: float sums are only bit-identical if accumulation
        # order equals row order across chunk boundaries
        batch, schema = _fused_batch(90_000, seed=19)
        n = batch.num_rows
        cuts = [0, n // 3, n // 3 + 1, 2 * n // 3, n]
        from hyperspace_tpu.io.columnar import Column, ColumnarBatch

        chunks = []
        for lo, hi in zip(cuts, cuts[1:]):
            chunks.append(
                ColumnarBatch(
                    {
                        nm: Column(
                            "numeric",
                            c.arrow_type,
                            values=c.values[lo:hi],
                            validity=None
                            if c.validity is None
                            else c.validity[lo:hi],
                        )
                        for nm, c in batch.columns.items()
                    }
                )
            )
        self._check(chunks, _TERMS, ["k"], _all_agg_specs(), schema)

    def test_group_growth_and_rebuild(self):
        # >> the 1024 initial capacity: forces the kernel's stop-grow-
        # rebuild handshake mid-chunk, repeatedly
        import pyarrow as pa

        from hyperspace_tpu.io.columnar import Column, ColumnarBatch
        from hyperspace_tpu.plan.nodes import AggSpec

        rng = np.random.default_rng(23)
        n = 150_000
        batch = ColumnarBatch(
            {
                "k": Column(
                    "numeric",
                    pa.int64(),
                    values=rng.integers(0, 1 << 62, n, dtype=np.int64),
                ),
                "a": Column(
                    "numeric",
                    pa.int64(),
                    values=rng.integers(-100, 100, n, dtype=np.int64),
                ),
            }
        )
        schema = {"k": pa.int64(), "a": pa.int64()}
        aggs = [AggSpec("count", None, "n"), AggSpec("sum", "a", "sa")]
        self._check(
            batch, (("a", -90, False, None, False, False),), ["k"], aggs,
            schema,
        )

    def test_empty_result_grouped_and_ungrouped(self):
        batch, schema = _fused_batch(20_000, seed=29)
        never = (("a", 1000, False, None, False, False),)
        self._check(batch, never, ["k"], _all_agg_specs(), schema)
        self._check(batch, never, [], _all_agg_specs(), schema)

    def test_int64_sum_wraparound(self):
        # numpy int64 sums wrap mod 2^64; the kernel accumulates as
        # uint64 for the same bit pattern instead of UB signed overflow
        import pyarrow as pa

        from hyperspace_tpu.io.columnar import Column, ColumnarBatch
        from hyperspace_tpu.plan.nodes import AggSpec

        n = 4096
        vals = np.full(n, (1 << 62) + 12345, dtype=np.int64)
        batch = ColumnarBatch(
            {
                "k": Column(
                    "numeric", pa.int64(), values=np.zeros(n, dtype=np.int64)
                ),
                "a": Column("numeric", pa.int64(), values=vals),
            }
        )
        schema = {"k": pa.int64(), "a": pa.int64()}
        self._check(
            batch,
            (("a", 0, False, None, False, False),),
            ["k"],
            [AggSpec("sum", "a", "sa")],
            schema,
        )

    def test_count_col_over_string_column(self):
        # COUNT(col) reads only the valid mask, so string columns are
        # countable through the fused pass
        import pyarrow as pa

        from hyperspace_tpu.io.columnar import Column, ColumnarBatch
        from hyperspace_tpu.plan.nodes import AggSpec

        batch, schema = _fused_batch(30_000, seed=31)
        scol = Column.from_arrow(
            pa.array(
                [
                    None if i % 7 == 0 else f"s{i % 11}"
                    for i in range(batch.num_rows)
                ]
            )
        )
        batch = batch.with_column("s", scol)
        schema = dict(schema)
        schema["s"] = pa.string()
        self._check(
            batch,
            _TERMS,
            ["k"],
            [AggSpec("count", "s", "ns"), AggSpec("count", None, "n")],
            schema,
        )

    def test_unsupported_shapes_bail_to_interpreter(self):
        # string group key / string min-max / sub-8-byte columns must
        # return None (the executor runs the interpreted chain)
        import pyarrow as pa

        from hyperspace_tpu.plan.nodes import AggSpec

        pc = _pc()
        batch, schema = _fused_batch(5000, seed=37)
        schema2 = dict(schema)
        schema2["s"] = pa.string()
        assert (
            pc.kernel_filter_aggregate(
                batch, _TERMS, ["s"], [AggSpec("count", None, "n")], schema2
            )
            is None
        )
        assert (
            pc.kernel_filter_aggregate(
                batch, _TERMS, ["k"], [AggSpec("min", "s", "m")], schema2
            )
            is None
        )
        schema3 = dict(schema)
        schema3["a"] = pa.int32()  # decodes to 4 bytes: not fusable
        assert (
            pc.kernel_filter_aggregate(
                batch, _TERMS, ["k"], [AggSpec("sum", "a", "sa")], schema3
            )
            is None
        )
