"""Aggregate index plane: differential + approximation property suite.

The contract under test (docs/agg-serve.md, indexes/aggindex.py,
execution/pipeline_compiler.try_metadata_aggregate): for every supported
``Filter(→Project)→Aggregate`` over a clean index scan, the metadata
plane's answer — fully-covered row groups folded from the persisted
``_aggstate.json`` partials, boundary row groups scanned — is
BIT-IDENTICAL to the fused pass and to the interpreted chain, across the
range-prune dtype matrix; incremental refresh folds only the appended
files' partials; a stale sidecar entry falls back per file (lazy
backfill); the sampling plane's 95% confidence intervals empirically
hold; and approximate answers are NEVER silently substituted for exact
ones.
"""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import functions as F
from hyperspace_tpu.exceptions import ApproximationError
from hyperspace_tpu.execution import pipeline_compiler as PC
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes import aggindex, zonemaps
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig
from hyperspace_tpu.io import parquet as pio


@pytest.fixture
def s1(session_factory):
    """Mesh-1 session: the metadata plane is a host serving substitution
    with no mesh axis."""
    return session_factory(1)


@pytest.fixture(autouse=True)
def _small_row_groups(monkeypatch):
    """Write index files with small row groups so test-sized fixtures
    exercise real FULL / boundary / EMPTY classification instead of one
    row group per file."""
    monkeypatch.setattr(pio, "INDEX_ROW_GROUP_SIZE", 512)
    aggindex.invalidate_local_cache()
    zonemaps.invalidate_local_cache()
    yield
    aggindex.invalidate_local_cache()
    zonemaps.invalidate_local_cache()


def _write_files(tmp_path, name, table, n_files=4):
    d = tmp_path / name
    d.mkdir()
    n = table.num_rows
    for i in range(n_files):
        lo, hi = i * n // n_files, (i + 1) * n // n_files
        pq.write_table(table.slice(lo, hi - lo), str(d / f"part{i}.parquet"))
    return str(d)


def _tables_bit_equal(a: pa.Table, b: pa.Table) -> None:
    assert a.schema.equals(b.schema), (a.schema, b.schema)
    assert a.num_rows == b.num_rows, (a.num_rows, b.num_rows)
    for name in a.column_names:
        ca = a.column(name).combine_chunks()
        cb = b.column(name).combine_chunks()
        assert ca.is_valid().equals(cb.is_valid()), name
        if pa.types.is_floating(ca.type):
            va = np.asarray(ca.fill_null(0.0)).view(np.int64)
            vb = np.asarray(cb.fill_null(0.0)).view(np.int64)
            np.testing.assert_array_equal(va, vb, err_msg=name)
        else:
            assert ca.equals(cb), name


def _four_way(session, q, expect_meta=True):
    """q() with (1) the metadata plane on, (2) plane off + fused on,
    (3) both off (interpreted chain), (4) unindexed. 1 ≡ 2 ≡ 3
    bit-identically; vs raw the row count must agree. Returns (metadata
    table, metadata-plane stats)."""
    session.enable_hyperspace()
    aggindex.invalidate_local_cache()
    zonemaps.invalidate_local_cache()
    PC.last_aggplane_stats = {}
    meta = q()
    stats = dict(PC.last_aggplane_stats)
    if expect_meta:
        assert stats.get("mode") == "agg_metadata", (
            f"metadata plane did not answer: {stats}"
        )
        assert stats["row_groups_metadata"] > 0, stats
    session.conf.set(C.INDEX_AGG_ENABLED, False)
    PC.last_aggplane_stats = {}
    fused = q()
    assert PC.last_aggplane_stats == {}, "metadata plane ran with flag off"
    session.conf.set(C.SERVE_FUSEDPIPELINE_ENABLED, False)
    interp = q()
    session.conf.unset(C.SERVE_FUSEDPIPELINE_ENABLED)
    session.conf.unset(C.INDEX_AGG_ENABLED)
    session.disable_hyperspace()
    raw = q()
    _tables_bit_equal(meta, fused)
    _tables_bit_equal(meta, interp)
    assert meta.num_rows == raw.num_rows, (meta.num_rows, raw.num_rows)
    return meta, stats


def _dtype_tables(rng, n=8000):
    """The range-prune dtype matrix with METADATA-MERGEABLE aggregates
    (count / count(col) / min / max / int sum / int avg / float min-max;
    float SUM stays on the fused path by contract and is covered by
    test_float_sum_declines_to_fused)."""
    base = np.datetime64("2019-01-01")
    days = np.sort(rng.integers(0, 900, n))

    def num_aggs(df):
        return (
            F.count().alias("n"),
            F.count("c").alias("nc"),
            F.min("c").alias("mn"),
            F.max("c").alias("mx"),
            F.sum("c").alias("sc"),
            F.avg("c").alias("ac"),
            F.min("v").alias("mnv"),
            F.max("v").alias("mxv"),
        )

    def temporal_aggs(df):
        return (
            F.count().alias("n"),
            F.min("c").alias("mn"),
            F.max("c").alias("mx"),
            F.min("v").alias("mnv"),
        )

    def count_only(df):
        return (F.count().alias("n"), F.count("c").alias("nc"))

    v = rng.normal(0, 5, n)
    common = {
        "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        "v": pa.array(v),
    }
    yield "ints", {
        "c": pa.array(np.sort(rng.integers(-1000, 1000, n)), type=pa.int64()),
        **common,
    }, lambda df: (df["c"] >= -800) & (df["c"] < 800), num_aggs
    f = np.sort(rng.normal(0, 100, n))
    f[::31] = np.nan
    yield "floats_nan", {
        "c": pa.array(f),
        **common,
    }, lambda df: (df["c"] > -250.0) & (df["c"] <= 250.0), (
        lambda df: (
            F.count().alias("n"),
            F.count("c").alias("nc"),
            F.min("c").alias("mn"),
            F.max("c").alias("mx"),
            F.sum("p").alias("sp"),
        )
    )
    yield "strings", {
        "c": pa.array([f"k{int(x):06d}" for x in rng.integers(0, 5000, n)]),
        "s": pa.array(np.sort(rng.integers(0, 4000, n)), type=pa.int64()),
        **common,
    }, lambda df: (df["s"] >= 100) & (df["s"] < 3900), count_only
    yield "dates", {
        "c": pa.array((base + days).astype("datetime64[D]")),
        **common,
    }, lambda df: (
        (df["c"] >= np.datetime64("2019-02-01"))
        & (df["c"] <= np.datetime64("2021-04-01"))
    ), temporal_aggs
    yield "ts_tz", {
        "c": pa.array(
            (base + days).astype("datetime64[us]"),
            type=pa.timestamp("us", tz="UTC"),
        ),
        **common,
    }, lambda df: (df["c"] >= "2019-02-01") & (df["c"] < "2021-04-01"), (
        temporal_aggs
    )
    yield "nullable_int", {
        "c": pa.array(
            [
                None if i % 11 == 0 else int(x)
                for i, x in enumerate(np.sort(rng.integers(0, 10_000, n)))
            ],
            type=pa.int64(),
        ),
        **common,
    }, lambda df: (df["c"] > 500) & (df["c"] <= 9500), (
        lambda df: (
            F.count().alias("n"),
            F.count("c").alias("nc"),
            F.min("c").alias("mn"),
            F.max("c").alias("mx"),
            F.sum("c").alias("sc"),
        )
    )


class TestMetadataPlaneMatrix:
    """Four-way differential (metadata ≡ fused ≡ interpreted ≡ unindexed
    row count) across the dtype matrix, grouped and ungrouped, over
    z-order (range-sorted) index scans with real FULL + boundary row
    groups."""

    def test_dtype_matrix_grouped(self, s1, tmp_path):
        hs = Hyperspace(s1)
        rng = np.random.default_rng(7)
        for name, arrays, cond_fn, agg_fn in _dtype_tables(rng):
            d = _write_files(tmp_path, name, pa.table(arrays))
            df = s1.read.parquet(d)
            icols = ["s"] if name == "strings" else ["c"]
            inc = [c for c in arrays if c not in icols]
            hs.create_index(
                df, ZOrderCoveringIndexConfig(f"z_{name}", icols, inc)
            )
            q = lambda: (
                df.filter(cond_fn(df))
                .group_by("p")
                .agg(*agg_fn(df))
                .collect()
            )
            out, stats = _four_way(s1, q)
            assert 0 < out.num_rows <= 10, (name, out.num_rows)
            hs.delete_index(f"z_{name}")
            hs.vacuum_index(f"z_{name}")
            s1.index_manager.clear_cache()

    def test_ungrouped_with_boundary(self, s1, tmp_path):
        """A range cutting through the sorted key: interior row groups
        answer from metadata, boundary row groups scan — merged result
        bit-identical, and the telemetry proves both paths ran."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(11)
        n = 8000
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 100_000, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 6, n), type=pa.int64()),
            "v": pa.array(rng.normal(10, 2, n)),
        }
        d = _write_files(tmp_path, "bnd", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(df, ZOrderCoveringIndexConfig("z_b", ["c"], ["p", "v"]))
        q = lambda: (
            df.filter((df["c"] >= 7_777) & (df["c"] < 77_777))
            .agg(
                F.count().alias("n"),
                F.min("v").alias("mnv"),
                F.max("v").alias("mxv"),
                F.sum("p").alias("sp"),
                F.avg("p").alias("ap"),
            )
            .collect()
        )
        out, stats = _four_way(s1, q)
        assert stats["row_groups_metadata"] > 0, stats
        assert stats["row_groups_scanned"] > 0, stats  # real boundary
        assert stats["rows_scanned"] > 0
        assert out.num_rows == 1

    def test_fully_covered_zero_rows_read(self, s1, tmp_path):
        """The headline: a fully-covered grouped point aggregate answers
        from the sidecar with ZERO parquet row groups read."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(13)
        n = 6000
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 50_000, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 8, n), type=pa.int64()),
            "v": pa.array(rng.normal(0, 5, n)),
        }
        d = _write_files(tmp_path, "full", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(df, ZOrderCoveringIndexConfig("z_f", ["c"], ["p", "v"]))
        q = lambda: (
            df.filter(df["c"] >= 0)
            .group_by("p")
            .agg(F.count().alias("n"), F.sum("c").alias("sc"))
            .collect()
        )
        out, stats = _four_way(s1, q)
        assert stats["row_groups_scanned"] == 0, stats
        assert stats["rows_scanned"] == 0, stats
        assert stats["row_groups_metadata"] == stats["row_groups_total"]

    def test_no_filter_via_aggregate_rule(self, s1, tmp_path):
        """AggregateIndexRule: a bare Aggregate∘Scan (no Filter) rewrites
        onto the covering index and answers entirely from metadata."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(17)
        n = 5000
        arrays = {
            "k": pa.array(rng.integers(0, 40, n), type=pa.int64()),
            "p": pa.array(rng.integers(0, 5, n), type=pa.int64()),
            "v": pa.array(rng.normal(0, 5, n)),
        }
        d = _write_files(tmp_path, "rule", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(df, CoveringIndexConfig("ci_r", ["k"], ["p", "v"]))
        q = lambda: (
            df.group_by("p")
            .agg(F.count().alias("n"), F.max("k").alias("mk"))
            .collect()
        )
        out, stats = _four_way(s1, q)
        assert stats["rows_scanned"] == 0, stats
        # float SUM keeps the rule OFF the plan (row order would
        # reassociate the sum vs the source scan)
        s1.enable_hyperspace()
        plan = (
            df.group_by("p").agg(F.sum("v").alias("sv")).explain()
        )
        assert "Hyperspace" not in plan, plan
        s1.disable_hyperspace()

    def test_float_sum_declines_to_fused(self, s1, tmp_path):
        """Float SUM/AVG partials don't merge bit-identically, so the
        metadata plane must decline and the fused pass must serve."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(19)
        n = 5000
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 5000, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 8, n), type=pa.int64()),
            "v": pa.array(rng.normal(0, 5, n)),
        }
        d = _write_files(tmp_path, "fsum", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(df, ZOrderCoveringIndexConfig("z_fs", ["c"], ["p", "v"]))
        s1.enable_hyperspace()
        PC.last_aggplane_stats = {}
        PC.last_fused_stats = {}
        old = PC._NATIVE_FUSED_PIPELINE_MIN_ROWS
        PC._NATIVE_FUSED_PIPELINE_MIN_ROWS = 1
        try:
            df.filter(df["c"] >= 0).group_by("p").agg(
                F.sum("v").alias("sv")
            ).collect()
        finally:
            PC._NATIVE_FUSED_PIPELINE_MIN_ROWS = old
        assert PC.last_aggplane_stats == {}, PC.last_aggplane_stats
        assert PC.last_fused_stats.get("mode") == "agg", PC.last_fused_stats
        s1.disable_hyperspace()

    def test_in_predicate_declines(self, s1, tmp_path):
        """IN-list conjuncts lower to a [min,max] HULL — sound for
        pruning, UNSOUND for full-coverage — so the strict lowering must
        decline and results must still match."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(23)
        n = 4000
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 3000, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 5, n), type=pa.int64()),
        }
        d = _write_files(tmp_path, "inq", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(df, ZOrderCoveringIndexConfig("z_in", ["c"], ["p"]))
        s1.enable_hyperspace()
        PC.last_aggplane_stats = {}
        got = (
            df.filter(df["c"].isin([5, 2900]))
            .agg(F.count().alias("n"))
            .collect()
        )
        assert PC.last_aggplane_stats == {}, PC.last_aggplane_stats
        s1.disable_hyperspace()
        raw = (
            df.filter(df["c"].isin([5, 2900]))
            .agg(F.count().alias("n"))
            .collect()
        )
        _tables_bit_equal(got, raw)


class TestPartialsTwin:
    """The PR-13 hook: kernel chunk-state snapshots and the numpy twin
    produce IDENTICAL partials, and finalize_partials(fold(chunks)) ==
    the single-pass result."""

    def _plan_and_batch(self, nulls=False):
        from hyperspace_tpu.io.columnar import ColumnarBatch
        from hyperspace_tpu.ops.filter import lower_range_terms

        rng = np.random.default_rng(29)
        n = 4000
        g = rng.integers(0, 12, n).astype(np.float64)
        g[::13] = np.nan
        g[::17] = -0.0
        v = rng.normal(0, 3, n)
        v[::23] = np.nan
        arrays = {
            "c": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
            "g": pa.array(
                [None if nulls and i % 19 == 0 else float(x) for i, x in enumerate(g)]
            ),
            "v": pa.array(v),
            "w": pa.array(rng.integers(-50, 50, n), type=pa.int64()),
        }
        batch = ColumnarBatch.from_arrow(pa.table(arrays))
        schema = {k: batch.column(k).arrow_type for k in arrays}
        from hyperspace_tpu.plan.nodes import AggSpec

        aggs = [
            AggSpec("count", None, "n"),
            AggSpec("count", "v", "nv"),
            AggSpec("sum", "w", "sw"),
            AggSpec("min", "v", "mnv"),
            AggSpec("max", "v", "mxv"),
            AggSpec("min", "w", "mnw"),
            AggSpec("max", "w", "mxw"),
        ]
        import hyperspace_tpu.plan.expressions as E

        cond = E.And(
            E.Ge(E.Col("c"), E.Lit(100)),
            E.Lt(E.Col("c"), E.Lit(900)),
        )
        terms = lower_range_terms(cond, batch)
        fplan = PC._lower_from_terms(terms, ("g",), aggs, schema)
        assert fplan is not None
        return fplan, batch

    def test_kernel_vs_numpy_partials(self, s1):
        from hyperspace_tpu import native
        from hyperspace_tpu.ops.filter import range_mask_numpy

        if native.load() is None:
            pytest.skip("native kernels unavailable")
        fplan, batch = self._plan_and_batch(nulls=True)
        state = PC.AggState(fplan)
        assert state.accumulate(batch)
        kp = state.partials()
        fb = batch.filter(range_mask_numpy(batch, fplan.terms))
        tp = PC.partials_from_batch(fplan, fb, rows_scanned=batch.num_rows)
        assert tp is not None
        # same group SET and per-group accumulators (the kernel's group
        # order is insertion order, the twin's is factorize order —
        # compare through the canonical finalize)
        a = PC.finalize_partials(fplan, kp).to_arrow()
        b = PC.finalize_partials(fplan, tp).to_arrow()
        _tables_bit_equal(a, b)

    def test_fold_equals_single_pass(self, s1):
        fplan, batch = self._plan_and_batch()
        from hyperspace_tpu.ops.filter import range_mask_numpy

        fb = batch.filter(range_mask_numpy(batch, fplan.terms))
        whole = PC.partials_from_batch(fplan, fb)
        acc = PC.PartialsAccumulator(fplan)
        step = 700
        for lo in range(0, fb.num_rows, step):
            idx = np.arange(lo, min(lo + step, fb.num_rows))
            acc.fold(PC.partials_from_batch(fplan, fb.take(idx)))
        a = PC.finalize_partials(fplan, whole).to_arrow()
        b = PC.finalize_partials(fplan, acc.snapshot()).to_arrow()
        _tables_bit_equal(a, b)


class TestLifecycle:
    def _mk(self, s1, tmp_path, name="lc", n=6000):
        hs = Hyperspace(s1)
        rng = np.random.default_rng(31)
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 40_000, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 6, n), type=pa.int64()),
            "w": pa.array(rng.integers(0, 4, n), type=pa.int64()),
            "v": pa.array(rng.normal(0, 5, n)),
        }
        d = _write_files(tmp_path, name, pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(
            df, CoveringIndexConfig(f"ci_{name}", ["c"], ["p", "w", "v"])
        )

        def q():
            # re-read per call: a refresh test appends source files, and
            # a stale DataFrame snapshot would defeat the signature match
            fresh = s1.read.parquet(d)
            return (
                fresh.filter(fresh["c"] >= 0)
                .group_by("p")
                .agg(F.count().alias("n"), F.sum("c").alias("sc"))
                .collect()
            )

        return hs, df, d, q

    def test_incremental_refresh_folds_appended(self, s1, tmp_path):
        """Incremental refresh writes a NEW version dir whose sidecar
        covers only the appended files; earlier dirs keep theirs, and
        the merged serve still answers from metadata."""
        hs, df, d, q = self._mk(s1, tmp_path, "inc")
        base_out, _ = _four_way(s1, q)
        idx_root = os.path.join(
            s1.conf.get(C.INDEX_SYSTEM_PATH), "ci_inc"
        )
        before = {
            p: os.path.getmtime(p)
            for p in _sidecar_paths(idx_root)
        }
        assert before
        extra = pa.table(
            {
                "c": pa.array([7, 39_999, 12_345], type=pa.int64()),
                "p": pa.array([1, 2, 3], type=pa.int64()),
                "w": pa.array([0, 1, 2], type=pa.int64()),
                "v": pa.array([1.0, 2.0, 3.0]),
            }
        )
        pq.write_table(extra, os.path.join(d, "part_extra.parquet"))
        hs.refresh_index("ci_inc", "incremental")
        after = _sidecar_paths(idx_root)
        assert len(after) == len(before) + 1  # one NEW dir sidecar
        for p, mt in before.items():
            assert os.path.getmtime(p) == mt  # old sidecars untouched
        out, stats = _four_way(s1, q)
        assert stats["rows_scanned"] == 0, stats
        assert out.num_rows >= base_out.num_rows

    def test_stale_sidecar_per_file_fallback(self, s1, tmp_path):
        """A sidecar whose entry no longer matches its file (size/mtime)
        must fall back PER FILE to lazy backfill — answers stay correct
        and the rest of the sidecar keeps serving."""
        hs, df, d, q = self._mk(s1, tmp_path, "stale")
        idx_root = os.path.join(
            s1.conf.get(C.INDEX_SYSTEM_PATH), "ci_stale"
        )
        side = _sidecar_paths(idx_root)[0]
        with open(side, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        victim = sorted(doc["files"])[0]
        doc["files"][victim]["mtime_ns"] = 1  # stale vs the real file
        with open(side, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        aggindex._sidecar_cached.cache_clear()
        aggindex.invalidate_local_cache()
        out, stats = _four_way(s1, q)
        assert stats["rows_scanned"] == 0, stats  # backfill covered it
        # and the assembly really took the backfill path for that file
        s1.enable_hyperspace()
        plan = s1.optimize(
            df.filter(df["c"] >= 0)
            .group_by("p")
            .agg(F.count().alias("n"))
            ._plan
        )
        s1.disable_hyperspace()

    def test_missing_sidecar_lazy_backfill(self, s1, tmp_path):
        """Pre-existing indexes (no sidecar at all) still get metadata
        answers: the per-file state is lazily computed from the files.
        Backfill restricts its grouped sweep to the QUERIED key — a
        later query grouping by a different key must trigger a fresh
        assembly (AggData.covers_key), not a silent decline."""
        hs, df, d, q = self._mk(s1, tmp_path, "nofile")
        idx_root = os.path.join(
            s1.conf.get(C.INDEX_SYSTEM_PATH), "ci_nofile"
        )
        for p in _sidecar_paths(idx_root):
            os.unlink(p)
        aggindex.invalidate_local_cache()
        out, stats = _four_way(s1, q)
        assert stats["rows_scanned"] == 0, stats
        # different group key over the SAME backfilled file set
        s1.enable_hyperspace()
        PC.last_aggplane_stats = {}
        fresh = s1.read.parquet(d)
        fresh.filter(fresh["c"] >= 0).group_by("w").agg(
            F.count().alias("n")
        ).collect()
        st2 = dict(PC.last_aggplane_stats)
        assert st2.get("mode") == "agg_metadata", st2
        assert st2["rows_scanned"] == 0, st2
        s1.disable_hyperspace()

    def test_vacuum_outdated_keeps_latest_sidecar(self, s1, tmp_path):
        """vacuum('outdated') drops old version dirs (sidecars die with
        them) but must NOT delete the retained dir's sidecars."""
        hs, df, d, q = self._mk(s1, tmp_path, "vac")
        pq.write_table(
            pa.table(
                {
                    "c": pa.array([5], type=pa.int64()),
                    "p": pa.array([0], type=pa.int64()),
                    "w": pa.array([0], type=pa.int64()),
                    "v": pa.array([1.0]),
                }
            ),
            os.path.join(d, "part_extra.parquet"),
        )
        hs.refresh_index("ci_vac", "full")
        idx_root = os.path.join(s1.conf.get(C.INDEX_SYSTEM_PATH), "ci_vac")
        # a crash-leaked publish temp in the retained dir: vacuum is its
        # only sweeper and must delete it while keeping the sidecars
        keep_dir = os.path.dirname(_sidecar_paths(idx_root)[-1])
        leak = os.path.join(keep_dir, "._aggstate.json.tmp.999")
        with open(leak, "w", encoding="utf-8") as fh:
            fh.write("{}")
        hs.vacuum_index("ci_vac")  # ACTIVE → outdated vacuum
        assert not os.path.exists(leak), "vacuum left the crash temp"
        remaining = _sidecar_paths(idx_root)
        assert remaining, "retained version dir lost its aggstate sidecar"
        out, stats = _four_way(s1, q)
        assert stats["rows_scanned"] == 0, stats

    def test_serve_cache_aggstate_kind(self, s1, tmp_path):
        """Serve-server mode caches the assembled state under
        ("aggstate", fp) and evict_kind reclaims it."""
        hs, df, d, q = self._mk(s1, tmp_path, "sc")
        s1.enable_hyperspace()
        s1.conf.set(C.SERVE_CACHE_ENABLED, True)
        try:
            q()
            kinds = {k[0] for k in s1.serve_cache._entries}
            assert "aggstate" in kinds, kinds
            assert s1.serve_cache.evict_kind("aggstate") >= 1
        finally:
            s1.conf.set(C.SERVE_CACHE_ENABLED, False)
            s1.clear_serve_cache()
            s1.disable_hyperspace()


def _sidecar_paths(idx_root):
    out = []
    for root, _dirs, names in os.walk(idx_root):
        for n in names:
            if n == aggindex.SIDECAR_NAME:
                out.append(os.path.join(root, n))
    return sorted(out)


class TestApproxPlane:
    def _mk(self, s1, tmp_path, n=20_000):
        hs = Hyperspace(s1)
        rng = np.random.default_rng(37)
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 100_000, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 6, n), type=pa.int64()),
            "v": pa.array(rng.gamma(4.0, 10.0, n)),  # positive: rel err sane
        }
        d = _write_files(tmp_path, "apx", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(df, ZOrderCoveringIndexConfig("z_apx", ["c"], ["p", "v"]))
        return hs, df

    def test_disabled_raises_and_exact_never_substituted(self, s1, tmp_path):
        hs, df = self._mk(s1, tmp_path, n=4000)
        s1.enable_hyperspace()
        dfq = df.filter(df["c"] >= 0).agg(F.count().alias("n"))
        with pytest.raises(ApproximationError):
            dfq.collect_approx()
        # approx enabled does NOT leak into exact collect()
        s1.conf.set(C.SERVE_APPROX_ENABLED, True)
        exact = dfq.collect()
        assert exact.column("n").to_pylist() == [4000]
        assert exact.schema.field("n").type == pa.int64()
        s1.conf.unset(C.SERVE_APPROX_ENABLED)
        s1.disable_hyperspace()

    def test_unapproximable_aggregates_raise(self, s1, tmp_path):
        hs, df = self._mk(s1, tmp_path, n=4000)
        s1.enable_hyperspace()
        s1.conf.set(C.SERVE_APPROX_ENABLED, True)
        try:
            with pytest.raises(ApproximationError):
                df.filter(df["c"] >= 0).agg(F.min("v").alias("m")).collect_approx()
            with pytest.raises(ApproximationError):
                # MULTI-key grouped: not estimable (single-key is — see
                # test_grouped_estimates_with_per_group_cis)
                df.filter(df["c"] >= 0).group_by("p", "c").agg(
                    F.count().alias("n")
                ).collect_approx()
        finally:
            s1.conf.unset(C.SERVE_APPROX_ENABLED)
            s1.disable_hyperspace()

    def test_budget_violation_raises(self, s1, tmp_path):
        hs, df = self._mk(s1, tmp_path)
        s1.enable_hyperspace()
        s1.conf.set(C.SERVE_APPROX_ENABLED, True)
        try:
            with pytest.raises(ApproximationError):
                # a near-empty selection: CI half-width dwarfs the tiny
                # estimate, the budget must reject it
                df.filter(df["c"] < 3).agg(
                    F.count().alias("n")
                ).collect_approx(max_rel_error=0.01)
        finally:
            s1.conf.unset(C.SERVE_APPROX_ENABLED)
            s1.disable_hyperspace()

    def test_grouped_estimates_with_per_group_cis(self, s1, tmp_path):
        """Single-key grouped COUNT/SUM: one row per observed group,
        key-sorted, each with its own 95% interval — and the intervals
        contain the exact answers (a seeded check, not probabilistic
        hand-waving: this seed's sample is fixed)."""
        hs, df = self._mk(s1, tmp_path)
        s1.enable_hyperspace()
        s1.conf.set(C.SERVE_APPROX_ENABLED, True)
        try:
            q = df.filter(df["c"] < 60_000).group_by("p").agg(
                F.count().alias("n"), F.sum("v").alias("sv")
            )
            approx = q.collect_approx(max_rel_error=0.9)
            exact = q.collect().sort_by([("p", "ascending")])
            assert approx.column_names == ["p", "n", "n_lo", "n_hi", "sv", "sv_lo", "sv_hi"]
            assert approx.column("p").to_pylist() == exact.column("p").to_pylist()
            an = approx.to_pydict()
            en = exact.to_pydict()
            held = sum(
                1
                for i in range(len(an["p"]))
                if an["n_lo"][i] <= en["n"][i] <= an["n_hi"][i]
            )
            # 95% intervals over 6 groups: tolerate one miss, no more
            assert held >= len(an["p"]) - 1, (an, en)
            for i in range(len(an["p"])):
                assert an["n_lo"][i] <= an["n"][i] <= an["n_hi"][i]
                assert an["sv_lo"][i] <= an["sv"][i] <= an["sv_hi"][i]
            # estimates are float64 — never mistakable for exact ints
            assert approx.schema.field("n").type == pa.float64()
        finally:
            s1.conf.unset(C.SERVE_APPROX_ENABLED)
            s1.disable_hyperspace()

    def test_grouped_budget_applies_per_group(self, s1, tmp_path):
        """A budget every group must hold: a rare group's wide interval
        rejects the whole answer rather than shipping one over-trusted
        row."""
        hs, df = self._mk(s1, tmp_path)
        s1.enable_hyperspace()
        s1.conf.set(C.SERVE_APPROX_ENABLED, True)
        try:
            with pytest.raises(ApproximationError):
                df.filter(df["c"] < 60_000).group_by("p").agg(
                    F.count().alias("n")
                ).collect_approx(max_rel_error=0.01)
        finally:
            s1.conf.unset(C.SERVE_APPROX_ENABLED)
            s1.disable_hyperspace()

    def test_single_sample_stratum_refused(self, s1, tmp_path):
        """A partially-sampled stratum with ONE sample row has no
        estimable variance — the estimator must refuse, never return a
        zero-width 'interval'."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(43)
        n = 4000
        s1.conf.set(C.INDEX_AGG_SAMPLE_ROWS, 1)
        try:
            d = _write_files(tmp_path, "one", pa.table({
                "c": pa.array(np.sort(rng.integers(0, 9000, n)), type=pa.int64()),
                "v": pa.array(rng.gamma(2.0, 3.0, n)),
            }))
            df = s1.read.parquet(d)
            hs.create_index(df, ZOrderCoveringIndexConfig("z_one", ["c"], ["v"]))
            s1.enable_hyperspace()
            s1.conf.set(C.SERVE_APPROX_ENABLED, True)
            with pytest.raises(ApproximationError):
                df.filter(df["c"] >= 0).agg(
                    F.count().alias("n")
                ).collect_approx(max_rel_error=1e9)
        finally:
            s1.conf.unset(C.INDEX_AGG_SAMPLE_ROWS)
            s1.conf.unset(C.SERVE_APPROX_ENABLED)
            s1.disable_hyperspace()

    def test_rewritten_file_never_serves_stale_samples(self, s1, tmp_path):
        """A data file rewritten under the same basename must sample from
        the fresh backfill read, never the dir sidecar's old rows."""
        hs, df = self._mk(s1, tmp_path, n=4000)
        rel_files = None
        s1.enable_hyperspace()
        s1.conf.set(C.SERVE_APPROX_ENABLED, True)
        try:
            sel = df.filter(df["c"] >= 0)
            before = sel.agg(F.count().alias("n")).collect_approx(
                max_rel_error=1e9
            )
            # dirty ONE index file's identity (stat changes; content-wise
            # this simulates a rewrite) and drop assembled caches
            idx_root = os.path.join(
                s1.conf.get(C.INDEX_SYSTEM_PATH), "z_apx"
            )
            victim = None
            for root, _dirs, names in os.walk(idx_root):
                for nme in sorted(names):
                    if nme.endswith(".parquet") and not nme.startswith("_"):
                        victim = os.path.join(root, nme)
                        break
                if victim:
                    break
            os.utime(victim, ns=(1, 1))
            aggindex.invalidate_local_cache()
            # the estimate must still be produced (backfilled sample for
            # the dirtied file) and still bracket the exact answer
            est = sel.agg(F.count().alias("n")).collect_approx(
                max_rel_error=1e9
            )
            s1.conf.set(C.SERVE_APPROX_ENABLED, False)
            truth = sel.agg(F.count().alias("n")).collect()
            s1.conf.set(C.SERVE_APPROX_ENABLED, True)
            tn = truth.column("n").to_pylist()[0]
            e = est.to_pydict()
            assert e["n_lo"][0] <= tn <= e["n_hi"][0], (e, tn)
        finally:
            s1.conf.unset(C.SERVE_APPROX_ENABLED)
            s1.disable_hyperspace()

    def test_error_bounds_hold(self, s1, tmp_path):
        """95% CIs over a battery of seeded range queries: coverage of
        the true COUNT/SUM must hold well above the coin-flip line (the
        battery shares one sample, so outcomes correlate; ≥85% observed
        coverage on 40 windows is the flake-proof assertion for a
        nominal 95% interval)."""
        hs, df = self._mk(s1, tmp_path)
        s1.enable_hyperspace()
        s1.conf.set(C.SERVE_APPROX_ENABLED, True)
        rng = np.random.default_rng(41)
        hits_n = hits_s = total = 0
        try:
            for _ in range(40):
                lo = int(rng.integers(0, 60_000))
                hi = lo + int(rng.integers(20_000, 40_000))
                sel = df.filter((df["c"] >= lo) & (df["c"] < hi))
                est = sel.agg(
                    F.count().alias("n"), F.sum("v").alias("sv")
                ).collect_approx(max_rel_error=1e9)
                s1.conf.set(C.SERVE_APPROX_ENABLED, False)
                truth = sel.agg(
                    F.count().alias("n"), F.sum("v").alias("sv")
                ).collect()
                s1.conf.set(C.SERVE_APPROX_ENABLED, True)
                tn = truth.column("n").to_pylist()[0]
                ts = truth.column("sv").to_pylist()[0] or 0.0
                e = est.to_pydict()
                total += 1
                if e["n_lo"][0] <= tn <= e["n_hi"][0]:
                    hits_n += 1
                if e["sv_lo"][0] <= ts <= e["sv_hi"][0]:
                    hits_s += 1
        finally:
            s1.conf.unset(C.SERVE_APPROX_ENABLED)
            s1.disable_hyperspace()
        assert hits_n / total >= 0.85, (hits_n, total)
        assert hits_s / total >= 0.85, (hits_s, total)
