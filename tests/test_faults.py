"""Fault-injection harness + the serve fault matrix.

The contract (ISSUE 8 / docs/serve-server.md): for each injection point
(parquet read, kernel dispatch, log read, cache insert, fastbus send) ×
{transient, persistent}, a serve through the frontend either RETRIES to
a bit-identical result or DEGRADES to a path with identical output —
never a wrong answer, never a hung query. Every leg also asserts its
point actually fired (``faults.stats()``), so a refactor that silently
bypasses an injection seam fails here, not in production.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import functions as hsf
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.testing import faults
from hyperspace_tpu.testing.faults import InjectedFault


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class TestFaultRegistry:
    def test_spec_parsing(self):
        assert faults.parse_spec("off") is None
        assert faults.parse_spec("") is None
        assert faults.parse_spec("transient") == (True, 1, None)
        assert faults.parse_spec("transient:3") == (True, 3, None)
        assert faults.parse_spec("persistent") == (False, None, None)
        assert faults.parse_spec("persistent;match=v__=") == (
            False,
            None,
            "v__=",
        )
        for bad in ("sometimes", "transient:0", "persistent:2", "transient;x=1"):
            with pytest.raises(ValueError):
                faults.parse_spec(bad)
        with pytest.raises(ValueError):
            faults.set_fault("not_a_point", "transient")

    def test_transient_budget_and_match(self):
        faults.set_fault("log_read", "transient:2;match=special")
        # non-matching detail never fires
        faults.check("log_read", "/other/path")
        with pytest.raises(InjectedFault) as ei:
            faults.check("log_read", "/special/path")
        assert ei.value.transient and ei.value.point == "log_read"
        with pytest.raises(InjectedFault):
            faults.check("log_read", "also special")
        # budget exhausted: recovered
        faults.check("log_read", "special again")
        assert faults.stats() == {"log_read": 2}

    def test_degraded_flavor_and_config_keyed_arming(self):
        from hyperspace_tpu.config import Config

        conf = Config()
        conf.set(C.FAULTS_KEY_PREFIX + "kernel_dispatch", "persistent")
        conf.set(C.FAULTS_KEY_PREFIX + "cache_insert", "off")
        assert faults.configure(conf) == 1
        assert faults.degraded("kernel_dispatch")
        assert faults.degraded("kernel_dispatch")  # persistent: every call
        assert not faults.degraded("cache_insert")
        faults.clear()
        assert not faults.degraded("kernel_dispatch")
        # cumulative totals survive clear()
        assert faults.stats()["kernel_dispatch"] == 2

    def test_injected_fault_is_oserror(self):
        # the transient classification path must treat injected and real
        # I/O faults identically (serve/frontend._is_transient)
        assert issubclass(InjectedFault, OSError)


# ---------------------------------------------------------------------------
# The serve fault matrix
# ---------------------------------------------------------------------------


@pytest.fixture
def served(session_factory, tmp_path):
    """One-device session, small indexed table, serve frontend, plus the
    fault-free baseline results computed up front (serial, no frontend)."""
    s = session_factory(1)
    d = tmp_path / "events"
    d.mkdir()
    rng = np.random.default_rng(3)
    n = 24_000
    t = pa.table(
        {
            "k": pa.array(rng.integers(0, 600, n), pa.int64()),
            "v": pa.array(rng.normal(0.0, 1.0, n)),
            "q": pa.array(rng.integers(1, 50, n), pa.int64()),
        }
    )
    for i in range(3):
        pq.write_table(t.slice(i * n // 3, n // 3), str(d / f"p{i}.parquet"))
    s.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
    hs = Hyperspace(s)
    df = s.read.parquet(str(d))
    hs.create_index(df, CoveringIndexConfig("i1", ["k"], ["v", "q"]))
    s.enable_hyperspace()

    def q_point(key=7):
        return df.filter(df["k"] == key).select("v", "q")

    def q_agg():
        return df.filter((df["k"] >= 100) & (df["k"] < 300)).agg(
            hsf.count().alias("n"), hsf.sum("q").alias("sq")
        )

    baselines = {
        "point": s.execute(q_point().logical_plan),
        "agg": s.execute(q_agg().logical_plan),
    }
    fe = s.serve_frontend
    yield s, fe, q_point, q_agg, baselines
    fe.close()


def _assert_bit_identical(got: pa.Table, want: pa.Table):
    assert got.schema.equals(want.schema)
    assert got.equals(want), (got.to_pydict(), want.to_pydict())


class TestFaultMatrix:
    @pytest.mark.parametrize("spec", ["transient:1", "transient:3"])
    def test_parquet_read_transient_retries(self, served, spec):
        s, fe, q_point, _q_agg, base = served
        faults.set_fault("parquet_read", spec)
        out = fe.serve(q_point())
        _assert_bit_identical(out, base["point"])
        assert faults.stats()["parquet_read"] >= 1
        assert fe.stats()["retries"] >= 1
        assert fe.stats()["failed"] == 0

    def test_parquet_read_persistent_degrades_to_source(self, served):
        s, fe, q_point, _q_agg, base = served
        # only INDEX data reads fail (version dirs are v__=N); the
        # degrade path — the unrewritten plan over source files — works
        faults.set_fault("parquet_read", "persistent;match=v__=")
        out = fe.serve(q_point())
        _assert_bit_identical(out, base["point"])
        assert faults.stats()["parquet_read"] >= 1
        assert fe.stats()["degraded"] >= 1

    def test_parquet_read_persistent_everywhere_fails_typed(self, served):
        # no healthy path left: the query must fail with the typed
        # injected fault — cleanly, not hang, and not a wrong answer
        s, fe, q_point, _q_agg, _base = served
        faults.set_fault("parquet_read", "persistent")
        with pytest.raises(InjectedFault):
            fe.serve(q_point())
        assert fe.stats()["failed"] >= 1

    @pytest.mark.parametrize("spec", ["transient:2", "persistent"])
    def test_kernel_dispatch_degrades_to_twins(self, served, spec):
        # every native kernel wrapper passes through load(wait=False);
        # a fired fault returns None and the caller runs the registered
        # numpy/interpreted twin (KERNEL_TWINS) — identical output with
        # no frontend involvement at all
        s, fe, q_point, q_agg, base = served
        faults.set_fault("kernel_dispatch", spec)
        _assert_bit_identical(fe.serve(q_agg()), base["agg"])
        _assert_bit_identical(fe.serve(q_point()), base["point"])
        assert faults.stats()["kernel_dispatch"] >= 1
        assert fe.stats()["failed"] == 0
        assert fe.stats()["degraded"] == 0  # degrade happened at dispatch

    def test_log_read_transient_retries_pin(self, served):
        s, fe, q_point, _q_agg, base = served
        s.index_manager.clear_cache()  # force a real log read at pin time
        faults.set_fault("log_read", "transient:1")
        out = fe.serve(q_point())
        _assert_bit_identical(out, base["point"])
        assert faults.stats()["log_read"] >= 1
        assert fe.stats()["failed"] == 0

    def test_log_read_persistent_serves_without_indexes(self, served):
        s, fe, q_point, _q_agg, base = served
        s.index_manager.clear_cache()
        faults.set_fault("log_read", "persistent")
        out = fe.serve(q_point())
        _assert_bit_identical(out, base["point"])
        assert faults.stats()["log_read"] >= 1
        assert fe.stats()["degraded_pins"] >= 1
        assert fe.stats()["failed"] == 0

    @pytest.mark.parametrize("spec", ["transient:1", "persistent"])
    def test_cache_insert_drops_never_fails(self, served, spec):
        s, fe, q_point, _q_agg, base = served
        s.conf.set(C.SERVE_CACHE_ENABLED, True)
        try:
            faults.set_fault("cache_insert", spec)
            _assert_bit_identical(fe.serve(q_point()), base["point"])
            want11 = s.execute(q_point(key=11).logical_plan)
            _assert_bit_identical(fe.serve(q_point(key=11)), want11)
            cache = s.serve_cache
            assert cache.insert_failures >= 1
            assert faults.stats()["cache_insert"] >= 1
            assert fe.stats()["failed"] == 0
            if spec == "transient:1":
                # recovered: later inserts land
                fe.serve(q_point(key=13))
                assert len(cache) >= 1
        finally:
            s.conf.set(C.SERVE_CACHE_ENABLED, False)
            s.clear_serve_cache()

    def test_every_point_fired_in_this_module(self, served):
        # matrix completeness backstop: arm everything transiently, run
        # one query per shape (plus one fast-plane push for the fleet
        # seam), and require ALL points to have fired at least once in
        # THIS test (budget sized for one serve each)
        s, fe, q_point, q_agg, base = served
        s.conf.set(C.SERVE_CACHE_ENABLED, True)
        try:
            s.index_manager.clear_cache()
            s.clear_serve_cache()
            faults.set_fault("parquet_read", "transient:1")
            faults.set_fault("kernel_dispatch", "transient:1")
            faults.set_fault("log_read", "transient:1")
            faults.set_fault("cache_insert", "transient:1")
            faults.set_fault("fastbus_send", "transient:1")
            _assert_bit_identical(fe.serve(q_agg()), base["agg"])
            _assert_bit_identical(fe.serve(q_point()), base["point"])
            from hyperspace_tpu.serve import fastbus

            with pytest.raises(InjectedFault):
                fastbus.push("/nonexistent.sock", {"type": "event"})
            fired = faults.stats()
            for point in faults.POINTS:
                assert fired.get(point, 0) >= 1, (point, fired)
        finally:
            s.conf.set(C.SERVE_CACHE_ENABLED, False)
            s.clear_serve_cache()


# ---------------------------------------------------------------------------
# The fleet fast plane's send seam (serve/fastbus.py)
# ---------------------------------------------------------------------------


class TestFastbusSend:
    """``fastbus_send`` × {transient, persistent}: an armed fault models
    a dead/unreachable peer socket at the fast data plane's send seam.
    The contract is pure degradation — pushes fall back to durable-poll
    delivery, routed requests fall back to the claim/spool single-flight
    — with bit-identical answers and zero raised errors on the serve
    path (``docs/fleet-serve.md``)."""

    def test_fault_raises_typed_oserror_at_the_seam(self, tmp_path):
        from hyperspace_tpu.serve import fastbus

        faults.set_fault("fastbus_send", "transient:1")
        with pytest.raises(InjectedFault):
            fastbus.push("/nonexistent.sock", {"type": "event"})
        assert faults.stats()["fastbus_send"] == 1
        # recovered: the next failed send is a plain dead-socket False,
        # not an injected raise
        assert not fastbus.push(str(tmp_path / "no.sock"), {"type": "e"})

    def test_push_fanout_degrades_without_raising(self, tmp_path):
        # router-level contract: an armed send fault never escapes
        # push_event_to_members — the durable poll is the retransmit
        import json as _json

        from hyperspace_tpu.serve import fastbus, router

        mdir = str(tmp_path / "members")
        os.makedirs(mdir)
        srv = fastbus.FastBusServer(lambda h, b: None)
        try:
            with open(os.path.join(mdir, "aa.json"), "w") as f:
                _json.dump(
                    {
                        "owner": "aa",
                        "pid": os.getpid(),
                        "sock": srv.path,
                        "expiresAtMs": int(__import__("time").time() * 1000)
                        + 60_000,
                    },
                    f,
                )
            members = router.read_members(mdir)
            faults.set_fault("fastbus_send", "persistent")
            delivered = 0
            for doc in members.values():
                try:
                    if fastbus.push(doc["sock"], {"type": "event"}):
                        delivered += 1
                except OSError:
                    continue  # the documented degrade: poll delivers
            assert delivered == 0
            assert faults.stats()["fastbus_send"] >= 1
            faults.set_fault("fastbus_send", "off")
            assert fastbus.push(srv.path, {"type": "event"})
        finally:
            srv.stop()

    @pytest.mark.parametrize("spec", ["transient:1", "persistent"])
    def test_routed_request_falls_back_bit_identical(
        self, spec, tmp_path, session_factory
    ):
        # end-to-end: two FleetFrontends over one lake; a query owned by
        # the PEER hits the armed send seam, falls back to the durable
        # claim/spool plane, and answers bit-identically
        from hyperspace_tpu.serve.router import rendezvous_owner
        from hyperspace_tpu.session import HyperspaceSession

        d = tmp_path / "flk"
        d.mkdir()
        rng = np.random.default_rng(9)
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(rng.integers(0, 50, 3000), pa.int64()),
                    "v": pa.array(rng.integers(-99, 99, 3000), pa.int64()),
                }
            ),
            str(d / "p0.parquet"),
        )
        idx = str(tmp_path / "flk_idx")

        def mk():
            s = HyperspaceSession()
            s.conf.set(C.INDEX_SYSTEM_PATH, idx)
            s.conf.set(C.INDEX_NUM_BUCKETS, 2)
            s.conf.set(C.FLEET_ENABLED, True)
            # park the gossip cadence: a maintenance-thread push must not
            # consume the transient fault budget before the probe does
            s.conf.set(C.FLEET_FAST_GOSSIP_MS, 60_000)
            s.enable_hyperspace()
            return s

        s1 = mk()
        hs = Hyperspace(s1)
        df = s1.read.parquet(str(d))
        hs.create_index(df, CoveringIndexConfig("flkidx", ["k"], ["v"]))
        s2 = mk()
        fe1, fe2 = s1.serve_frontend, s2.serve_frontend
        try:
            members = fe1._router.members(refresh=True)
            pin = fe1._pin()
            probe = None
            for kk in range(200):
                q = s1.read.parquet(str(d))
                q = q.filter((q["k"] == kk % 50) & (q["v"] > -1000 - kk))
                dig = fe1._plan_digest(q.logical_plan, pin)
                if rendezvous_owner(members.keys(), dig) == fe2._router.owner:
                    probe = q
                    break
            assert probe is not None
            faults.set_fault("fastbus_send", spec)
            got = fe1.serve(probe)
            faults.set_fault("fastbus_send", "off")
            s1.disable_hyperspace()
            want = probe.collect()
            s1.enable_hyperspace()
            got = got.sort_by([(c, "ascending") for c in got.column_names])
            want = want.sort_by(
                [(c, "ascending") for c in want.column_names]
            )
            assert got.equals(want)
            st = fe1.stats()["fleet"]
            assert st["fast_fallbacks"] >= 1, st
            assert faults.stats()["fastbus_send"] >= 1
            assert fe1.stats()["failed"] == 0
        finally:
            faults.set_fault("fastbus_send", "off")
            fe1.close()
            fe2.close()
