"""Fault-injection harness + the serve fault matrix.

The contract (ISSUE 8 / docs/serve-server.md): for each injection point
(parquet read, kernel dispatch, log read, cache insert) × {transient,
persistent}, a serve through the frontend either RETRIES to a
bit-identical result or DEGRADES to a path with identical output —
never a wrong answer, never a hung query. Every leg also asserts its
point actually fired (``faults.stats()``), so a refactor that silently
bypasses an injection seam fails here, not in production.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import functions as hsf
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.testing import faults
from hyperspace_tpu.testing.faults import InjectedFault


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class TestFaultRegistry:
    def test_spec_parsing(self):
        assert faults.parse_spec("off") is None
        assert faults.parse_spec("") is None
        assert faults.parse_spec("transient") == (True, 1, None)
        assert faults.parse_spec("transient:3") == (True, 3, None)
        assert faults.parse_spec("persistent") == (False, None, None)
        assert faults.parse_spec("persistent;match=v__=") == (
            False,
            None,
            "v__=",
        )
        for bad in ("sometimes", "transient:0", "persistent:2", "transient;x=1"):
            with pytest.raises(ValueError):
                faults.parse_spec(bad)
        with pytest.raises(ValueError):
            faults.set_fault("not_a_point", "transient")

    def test_transient_budget_and_match(self):
        faults.set_fault("log_read", "transient:2;match=special")
        # non-matching detail never fires
        faults.check("log_read", "/other/path")
        with pytest.raises(InjectedFault) as ei:
            faults.check("log_read", "/special/path")
        assert ei.value.transient and ei.value.point == "log_read"
        with pytest.raises(InjectedFault):
            faults.check("log_read", "also special")
        # budget exhausted: recovered
        faults.check("log_read", "special again")
        assert faults.stats() == {"log_read": 2}

    def test_degraded_flavor_and_config_keyed_arming(self):
        from hyperspace_tpu.config import Config

        conf = Config()
        conf.set(C.FAULTS_KEY_PREFIX + "kernel_dispatch", "persistent")
        conf.set(C.FAULTS_KEY_PREFIX + "cache_insert", "off")
        assert faults.configure(conf) == 1
        assert faults.degraded("kernel_dispatch")
        assert faults.degraded("kernel_dispatch")  # persistent: every call
        assert not faults.degraded("cache_insert")
        faults.clear()
        assert not faults.degraded("kernel_dispatch")
        # cumulative totals survive clear()
        assert faults.stats()["kernel_dispatch"] == 2

    def test_injected_fault_is_oserror(self):
        # the transient classification path must treat injected and real
        # I/O faults identically (serve/frontend._is_transient)
        assert issubclass(InjectedFault, OSError)


# ---------------------------------------------------------------------------
# The serve fault matrix
# ---------------------------------------------------------------------------


@pytest.fixture
def served(session_factory, tmp_path):
    """One-device session, small indexed table, serve frontend, plus the
    fault-free baseline results computed up front (serial, no frontend)."""
    s = session_factory(1)
    d = tmp_path / "events"
    d.mkdir()
    rng = np.random.default_rng(3)
    n = 24_000
    t = pa.table(
        {
            "k": pa.array(rng.integers(0, 600, n), pa.int64()),
            "v": pa.array(rng.normal(0.0, 1.0, n)),
            "q": pa.array(rng.integers(1, 50, n), pa.int64()),
        }
    )
    for i in range(3):
        pq.write_table(t.slice(i * n // 3, n // 3), str(d / f"p{i}.parquet"))
    s.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
    hs = Hyperspace(s)
    df = s.read.parquet(str(d))
    hs.create_index(df, CoveringIndexConfig("i1", ["k"], ["v", "q"]))
    s.enable_hyperspace()

    def q_point(key=7):
        return df.filter(df["k"] == key).select("v", "q")

    def q_agg():
        return df.filter((df["k"] >= 100) & (df["k"] < 300)).agg(
            hsf.count().alias("n"), hsf.sum("q").alias("sq")
        )

    baselines = {
        "point": s.execute(q_point().logical_plan),
        "agg": s.execute(q_agg().logical_plan),
    }
    fe = s.serve_frontend
    yield s, fe, q_point, q_agg, baselines
    fe.close()


def _assert_bit_identical(got: pa.Table, want: pa.Table):
    assert got.schema.equals(want.schema)
    assert got.equals(want), (got.to_pydict(), want.to_pydict())


class TestFaultMatrix:
    @pytest.mark.parametrize("spec", ["transient:1", "transient:3"])
    def test_parquet_read_transient_retries(self, served, spec):
        s, fe, q_point, _q_agg, base = served
        faults.set_fault("parquet_read", spec)
        out = fe.serve(q_point())
        _assert_bit_identical(out, base["point"])
        assert faults.stats()["parquet_read"] >= 1
        assert fe.stats()["retries"] >= 1
        assert fe.stats()["failed"] == 0

    def test_parquet_read_persistent_degrades_to_source(self, served):
        s, fe, q_point, _q_agg, base = served
        # only INDEX data reads fail (version dirs are v__=N); the
        # degrade path — the unrewritten plan over source files — works
        faults.set_fault("parquet_read", "persistent;match=v__=")
        out = fe.serve(q_point())
        _assert_bit_identical(out, base["point"])
        assert faults.stats()["parquet_read"] >= 1
        assert fe.stats()["degraded"] >= 1

    def test_parquet_read_persistent_everywhere_fails_typed(self, served):
        # no healthy path left: the query must fail with the typed
        # injected fault — cleanly, not hang, and not a wrong answer
        s, fe, q_point, _q_agg, _base = served
        faults.set_fault("parquet_read", "persistent")
        with pytest.raises(InjectedFault):
            fe.serve(q_point())
        assert fe.stats()["failed"] >= 1

    @pytest.mark.parametrize("spec", ["transient:2", "persistent"])
    def test_kernel_dispatch_degrades_to_twins(self, served, spec):
        # every native kernel wrapper passes through load(wait=False);
        # a fired fault returns None and the caller runs the registered
        # numpy/interpreted twin (KERNEL_TWINS) — identical output with
        # no frontend involvement at all
        s, fe, q_point, q_agg, base = served
        faults.set_fault("kernel_dispatch", spec)
        _assert_bit_identical(fe.serve(q_agg()), base["agg"])
        _assert_bit_identical(fe.serve(q_point()), base["point"])
        assert faults.stats()["kernel_dispatch"] >= 1
        assert fe.stats()["failed"] == 0
        assert fe.stats()["degraded"] == 0  # degrade happened at dispatch

    def test_log_read_transient_retries_pin(self, served):
        s, fe, q_point, _q_agg, base = served
        s.index_manager.clear_cache()  # force a real log read at pin time
        faults.set_fault("log_read", "transient:1")
        out = fe.serve(q_point())
        _assert_bit_identical(out, base["point"])
        assert faults.stats()["log_read"] >= 1
        assert fe.stats()["failed"] == 0

    def test_log_read_persistent_serves_without_indexes(self, served):
        s, fe, q_point, _q_agg, base = served
        s.index_manager.clear_cache()
        faults.set_fault("log_read", "persistent")
        out = fe.serve(q_point())
        _assert_bit_identical(out, base["point"])
        assert faults.stats()["log_read"] >= 1
        assert fe.stats()["degraded_pins"] >= 1
        assert fe.stats()["failed"] == 0

    @pytest.mark.parametrize("spec", ["transient:1", "persistent"])
    def test_cache_insert_drops_never_fails(self, served, spec):
        s, fe, q_point, _q_agg, base = served
        s.conf.set(C.SERVE_CACHE_ENABLED, True)
        try:
            faults.set_fault("cache_insert", spec)
            _assert_bit_identical(fe.serve(q_point()), base["point"])
            want11 = s.execute(q_point(key=11).logical_plan)
            _assert_bit_identical(fe.serve(q_point(key=11)), want11)
            cache = s.serve_cache
            assert cache.insert_failures >= 1
            assert faults.stats()["cache_insert"] >= 1
            assert fe.stats()["failed"] == 0
            if spec == "transient:1":
                # recovered: later inserts land
                fe.serve(q_point(key=13))
                assert len(cache) >= 1
        finally:
            s.conf.set(C.SERVE_CACHE_ENABLED, False)
            s.clear_serve_cache()

    def test_every_point_fired_in_this_module(self, served):
        # matrix completeness backstop: arm everything transiently, run
        # one query per shape, and require all four points to have fired
        # at least once in THIS test (budget sized for one serve each)
        s, fe, q_point, q_agg, base = served
        s.conf.set(C.SERVE_CACHE_ENABLED, True)
        try:
            s.index_manager.clear_cache()
            s.clear_serve_cache()
            faults.set_fault("parquet_read", "transient:1")
            faults.set_fault("kernel_dispatch", "transient:1")
            faults.set_fault("log_read", "transient:1")
            faults.set_fault("cache_insert", "transient:1")
            _assert_bit_identical(fe.serve(q_agg()), base["agg"])
            _assert_bit_identical(fe.serve(q_point()), base["point"])
            fired = faults.stats()
            for point in faults.POINTS:
                assert fired.get(point, 0) >= 1, (point, fired)
        finally:
            s.conf.set(C.SERVE_CACHE_ENABLED, False)
            s.clear_serve_cache()
