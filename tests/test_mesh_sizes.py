"""Cross-mesh layout compatibility.

The index layout contract (murmur3 bucket of the key VALUES, one file per
bucket) must be independent of the mesh that built it: an index built on
an 8-shard mesh serves correctly from a 1-device session and vice versa
(the reference's equivalent: bucketed data written by any cluster size is
readable by any other, HashPartitioning is value-determined).
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig


@pytest.fixture
def dataset(tmp_path):
    rng = np.random.default_rng(21)
    d = tmp_path / "xm"
    d.mkdir()
    for i in range(4):
        t = pa.table(
            {
                "k": pa.array(rng.integers(0, 100, 500), type=pa.int64()),
                "p": pa.array(rng.integers(0, 100, 500), type=pa.int64()),
            }
        )
        pq.write_table(t, d / f"f{i}.parquet")
    return str(d)


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


@pytest.mark.parametrize(
    "build_devs,serve_devs", [(8, 1), (1, 8)], ids=["b8s1", "b1s8"]
)
def test_build_serve_cross_mesh(session_factory, dataset, build_devs, serve_devs):
    builder = session_factory(build_devs)
    hs = Hyperspace(builder)
    df = builder.read.parquet(dataset)
    hs.create_index(df, CoveringIndexConfig("xidx", ["k"], ["p"]))

    server = session_factory(serve_devs)
    assert server.runtime.num_shards == serve_devs
    dfs = server.read.parquet(dataset)
    q = lambda d: d.filter(d["k"] == 42).select("k", "p")
    server.disable_hyperspace()
    base = q(dfs).collect()
    server.enable_hyperspace()
    plan = q(dfs).explain()
    assert "Hyperspace(Type: CI, Name: xidx" in plan
    got = q(dfs).collect()
    assert sorted_table(got).equals(sorted_table(base))
    assert got.num_rows > 0


@pytest.mark.parametrize(
    "build_devs,serve_devs", [(8, 1), (1, 8)], ids=["b8s1", "b1s8"]
)
def test_join_cross_mesh(session_factory, dataset, tmp_path, build_devs, serve_devs):
    rng = np.random.default_rng(5)
    d2 = tmp_path / "dim"
    d2.mkdir()
    t = pa.table(
        {
            "j": pa.array(np.arange(100), type=pa.int64()),
            "w": pa.array(rng.normal(size=100)),
        }
    )
    pq.write_table(t, d2 / "dim.parquet")

    builder = session_factory(build_devs)
    hs = Hyperspace(builder)
    fact = builder.read.parquet(dataset)
    dim = builder.read.parquet(str(d2))
    hs.create_index(fact, CoveringIndexConfig("fidx", ["k"], ["p"]))
    hs.create_index(dim, CoveringIndexConfig("didx", ["j"], ["w"]))

    server = session_factory(serve_devs)
    f2 = server.read.parquet(dataset)
    d2f = server.read.parquet(str(d2))
    q = lambda a, b: a.join(b, on=a["k"] == b["j"]).select("k", "p", "w")
    server.disable_hyperspace()
    base = q(f2, d2f).collect()
    server.enable_hyperspace()
    plan = q(f2, d2f).explain()
    assert plan.count("Hyperspace(Type: CI") == 2
    got = q(f2, d2f).collect()
    assert sorted_table(got).equals(sorted_table(base))
    assert got.num_rows > 0


def test_build_num_shards_caps_build_mesh(session_factory):
    """`hyperspace.build.numShards` caps the build-plane mesh to the
    first N devices (0 = the whole session mesh) — the IndexerContext
    is where every build stage reads its mesh from."""
    from hyperspace_tpu.indexes.context import IndexerContext
    from hyperspace_tpu.metadata.entry import FileIdTracker

    session = session_factory(8)
    ctx = IndexerContext(session, FileIdTracker(), "unused")
    assert ctx.mesh.devices.size == 8

    session.conf.set(C.BUILD_NUM_SHARDS, 2)
    capped = IndexerContext(session, FileIdTracker(), "unused")
    assert capped.mesh.devices.size == 2
    # memoized per context: both reads see one mesh object
    assert capped.mesh is capped.mesh
    # 0 and >mesh-size leave the session mesh untouched
    session.conf.set(C.BUILD_NUM_SHARDS, 0)
    assert IndexerContext(session, FileIdTracker(), "unused").mesh.devices.size == 8
    session.conf.set(C.BUILD_NUM_SHARDS, 64)
    assert IndexerContext(session, FileIdTracker(), "unused").mesh.devices.size == 8
