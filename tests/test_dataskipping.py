"""Data-skipping index tests.

Mirrors ``dataskipping/DataSkippingIndexIntegrationTest.scala`` and the
sketch unit suites: per-file sketch build, predicate translation,
file pruning at serve time, refresh, and losing to covering on score.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.dataskipping import DataSkippingIndexConfig
from hyperspace_tpu.indexes.sketches import (
    BloomFilterSketch,
    MinMaxSketch,
    PartitionSketch,
)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


@pytest.fixture
def ranged_parquet(tmp_path):
    """4 files with disjoint clicks ranges -> ideal for min/max pruning."""
    d = tmp_path / "ranged"
    d.mkdir()
    for i in range(4):
        t = pa.table(
            {
                "clicks": pa.array(
                    range(i * 1000, i * 1000 + 100), type=pa.int64()
                ),
                "name": [f"file{i}"] * 100,
                "part": [f"p{i}"] * 100,
            }
        )
        pq.write_table(t, d / f"f{i}.parquet")
    return str(d)


def scanned_files(session, df_plan):
    leaves = session.optimize(df_plan).collect_leaves()
    return leaves[0].relation.files


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


class TestMinMaxSkipping:
    def test_prunes_files_and_matches(self, session, hs, ranged_parquet):
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df, DataSkippingIndexConfig("ds", MinMaxSketch("clicks"))
        )
        session.enable_hyperspace()
        q = lambda d: d.filter(d["clicks"] == 2050).select("clicks", "name")
        plan_files = scanned_files(session, q(df).logical_plan)
        assert len(plan_files) == 1 and "f2.parquet" in plan_files[0]
        plan = q(df).explain()
        assert "Hyperspace(Type: DS, Name: ds" in plan
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        got = q(df).collect()
        assert sorted_table(got).equals(sorted_table(base))
        assert got.num_rows == 1

    def test_range_and_in_predicates(self, session, hs, ranged_parquet):
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df, DataSkippingIndexConfig("ds", MinMaxSketch("clicks"))
        )
        session.enable_hyperspace()
        f = scanned_files(
            session, df.filter(df["clicks"] < 1050).select("clicks").logical_plan
        )
        assert len(f) == 2  # f0 fully, f1 partially
        f = scanned_files(
            session,
            df.filter(df["clicks"].isin(5, 3005)).select("clicks").logical_plan,
        )
        assert len(f) == 2
        # conjunct with untranslatable part still prunes on the other
        f = scanned_files(
            session,
            df.filter((df["clicks"] == 5) & (df["name"] != "x"))
            .select("clicks")
            .logical_plan,
        )
        assert len(f) == 1

    def test_untranslatable_predicate_no_rewrite(
        self, session, hs, ranged_parquet
    ):
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df, DataSkippingIndexConfig("ds", MinMaxSketch("clicks"))
        )
        session.enable_hyperspace()
        plan = df.filter(df["name"] == "file1").select("name").explain()
        assert "Hyperspace" not in plan


class TestBloomSkipping:
    def test_bloom_prunes_string_equality(self, session, hs, ranged_parquet):
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df,
            DataSkippingIndexConfig(
                "dsb", BloomFilterSketch("name", 0.01, 1000)
            ),
        )
        session.enable_hyperspace()
        q = lambda d: d.filter(d["name"] == "file3").select("clicks", "name")
        files = scanned_files(session, q(df).logical_plan)
        assert len(files) == 1 and "f3.parquet" in files[0]
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df).collect()).equals(sorted_table(base))

    def test_bloom_float_literal_on_int_column(self, session, hs, ranged_parquet):
        """A float literal the executor would match (2050.0 == 2050) must
        NOT be pruned away by bit-exact rep hashing."""
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df,
            DataSkippingIndexConfig("dsb", BloomFilterSketch("clicks", 0.01, 1000)),
        )
        session.enable_hyperspace()
        q = lambda d: d.filter(d["clicks"] == 2050.0).select("clicks")
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        got = q(df).collect()
        assert got.num_rows == base.num_rows == 1
        # non-integral literal matches nothing -> pruned to zero files
        files = scanned_files(
            session, df.filter(df["clicks"] == 2050.5).select("clicks").logical_plan
        )
        assert files == ()

    def test_minmax_in_with_incomparable_literal(self, session, hs, ranged_parquet):
        """One bad IN value must make the sketch abstain, not kill the
        whole optimizer pass."""
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df, DataSkippingIndexConfig("ds", MinMaxSketch("clicks"))
        )
        session.enable_hyperspace()
        # untranslatable -> no DS rewrite, but no crash/fallback either
        out = df.filter(df["clicks"].isin(5, "a")).select("clicks").collect()
        assert out.num_rows == 1

    def test_modified_file_not_scanned_twice_hybrid(
        self, session, hs, ranged_parquet
    ):
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df, DataSkippingIndexConfig("ds", MinMaxSketch("clicks"))
        )
        # overwrite f2 in place, keeping a matching row
        pq.write_table(
            pa.table(
                {
                    "clicks": pa.array([2050, 2051], type=pa.int64()),
                    "name": ["file2x"] * 2,
                    "part": ["p2"] * 2,
                }
            ),
            os.path.join(ranged_parquet, "f2.parquet"),
        )
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(ranged_parquet)
        q = lambda d: d.filter(d["clicks"] == 2050).select("clicks", "name")
        session.disable_hyperspace()
        base = q(df2).collect()
        session.enable_hyperspace()
        got = q(df2).collect()
        assert got.num_rows == base.num_rows == 1  # no duplicated rows

    def test_bloom_numeric_in(self, session, hs, ranged_parquet):
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df,
            DataSkippingIndexConfig("dsb", BloomFilterSketch("clicks", 0.01, 1000)),
        )
        session.enable_hyperspace()
        files = scanned_files(
            session,
            df.filter(df["clicks"].isin(50, 1050)).select("clicks").logical_plan,
        )
        assert len(files) == 2


class TestPartitionSketch:
    def test_constant_column_pruning(self, session, hs, ranged_parquet):
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df, DataSkippingIndexConfig("dsp", PartitionSketch("part"))
        )
        session.enable_hyperspace()
        q = lambda d: d.filter(d["part"] == "p1").select("clicks", "part")
        files = scanned_files(session, q(df).logical_plan)
        assert len(files) == 1 and "f1.parquet" in files[0]
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df).collect()).equals(sorted_table(base))


class TestDataSkippingLifecycle:
    def test_covering_index_outranks_dataskipping(
        self, session, hs, ranged_parquet
    ):
        from hyperspace_tpu.indexes.covering import CoveringIndexConfig

        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df, DataSkippingIndexConfig("ds", MinMaxSketch("clicks"))
        )
        hs.create_index(df, CoveringIndexConfig("ci", ["clicks"], ["name"]))
        session.enable_hyperspace()
        plan = df.filter(df["clicks"] == 5).select("clicks", "name").explain()
        assert "Type: CI" in plan and "Type: DS" not in plan

    def test_refresh_incremental_append_and_delete(
        self, session, hs, ranged_parquet
    ):
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df, DataSkippingIndexConfig("ds", MinMaxSketch("clicks"))
        )
        os.remove(os.path.join(ranged_parquet, "f0.parquet"))
        pq.write_table(
            pa.table(
                {
                    "clicks": pa.array(range(9000, 9100), type=pa.int64()),
                    "name": ["file9"] * 100,
                    "part": ["p9"] * 100,
                }
            ),
            os.path.join(ranged_parquet, "f9.parquet"),
        )
        hs.refresh_index("ds", "incremental")
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(ranged_parquet)
        q = lambda d: d.filter(d["clicks"] == 9050).select("clicks", "name")
        files = scanned_files(session, q(df2).logical_plan)
        assert len(files) == 1 and "f9.parquet" in files[0]
        session.disable_hyperspace()
        base = q(df2).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df2).collect()).equals(sorted_table(base))

    def test_sketch_roundtrip_serialization(self, session, hs, ranged_parquet):
        df = session.read.parquet(ranged_parquet)
        hs.create_index(
            df,
            DataSkippingIndexConfig(
                "ds",
                MinMaxSketch("clicks"),
                BloomFilterSketch("name", 0.05, 500),
            ),
        )
        session.index_manager.clear_cache()
        entry = session.index_manager.get_index_log_entry("ds")
        kinds = {s.kind for s in entry.derived_dataset.sketches}
        assert kinds == {"MinMaxSketch", "BloomFilterSketch"}


class TestValueRepUint64:
    def test_uint64_probe_matches_bit_view(self):
        """uint64 literals >= 2^63 must probe with the int64 bit-view that
        io/columnar assigns as the column key_rep (advisor round-1 low)."""
        import numpy as np

        from hyperspace_tpu.indexes.sketches import _NO_MATCH, _value_rep

        v = (1 << 63) + 12345
        rep = _value_rep(v, "uint64")
        assert rep == int(np.uint64(v).view(np.int64))
        assert rep < 0  # bit-view wraps negative; np.array([rep]) can't overflow
        assert _value_rep(1 << 64, "uint64") is _NO_MATCH
        assert _value_rep(-1, "uint64") is _NO_MATCH
        assert _value_rep((1 << 63) + 12345, "int64") is _NO_MATCH
        assert _value_rep(42, "uint32") == 42
