"""Log-entry model tests (reference: index/IndexLogEntryTest.scala)."""

import pytest

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.indexes.covering import CoveringIndex
from hyperspace_tpu.metadata.entry import (
    Content,
    Directory,
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    LogicalPlanFingerprint,
    Relation,
    Signature,
    Source,
    SourcePlan,
)


def make_entry(state="ACTIVE", num_buckets=8):
    src_content = Content.from_leaf_files(
        [("/data/t/part-0.parquet", 100, 1000), ("/data/t/part-1.parquet", 200, 2000)]
    )
    idx_content = Content.from_leaf_files(
        [("/idx/v__=0/part-00000.parquet", 10, 1)]
    )
    index = CoveringIndex(["k"], ["v"], "{}", num_buckets)
    rel = Relation(["/data/t"], src_content, "{}", "parquet")
    return IndexLogEntry(
        name="myIndex",
        derived_dataset=index,
        content=idx_content,
        source=Source(SourcePlan([rel])),
        fingerprint=LogicalPlanFingerprint([Signature("file", "abc123")]),
        state=state,
        id=2,
    )


def test_fileinfo_equality_ignores_id():
    a = FileInfo("f", 1, 2, id=5)
    b = FileInfo("f", 1, 2, id=9)
    assert a == b and hash(a) == hash(b)
    assert a != FileInfo("f", 1, 3, id=5)


def test_directory_from_leaf_files_builds_tree():
    c = Content.from_leaf_files(
        [
            ("/a/b/f1", 1, 10),
            ("/a/b/f2", 2, 20),
            ("/a/c/f3", 3, 30),
        ]
    )
    assert sorted(c.files) == ["/a/b/f1", "/a/b/f2", "/a/c/f3"]
    assert c.size_in_bytes == 6
    root = c.root
    assert root.name == "/"
    assert [d.name for d in root.subdirs] == ["a"]
    assert sorted(d.name for d in root.subdirs[0].subdirs) == ["b", "c"]


def test_directory_merge_unions_files():
    c1 = Content.from_leaf_files([("/a/b/f1", 1, 10), ("/a/b/f2", 2, 20)])
    c2 = Content.from_leaf_files([("/a/b/f2", 2, 20), ("/a/d/f4", 4, 40)])
    merged = c1.merge(c2)
    assert sorted(merged.files) == ["/a/b/f1", "/a/b/f2", "/a/d/f4"]
    assert merged.size_in_bytes == 7


def test_directory_merge_name_mismatch_raises():
    with pytest.raises(HyperspaceException):
        Directory("a").merge(Directory("b"))


def test_file_id_tracker_stable_ids():
    t = FileIdTracker()
    a = t.add_file("/x/f1", 1, 10)
    b = t.add_file("/x/f2", 2, 20)
    assert (a, b) == (0, 1)
    assert t.add_file("/x/f1", 1, 10) == 0      # stable
    assert t.add_file("/x/f1", 1, 99) == 2      # modified file = new id
    assert t.max_id == 2
    mapping = dict(t.id_to_file_mapping())
    assert mapping[0] == "/x/f1" and mapping[1] == "/x/f2"


def test_file_id_tracker_seed_conflict():
    t = FileIdTracker()
    t.add_file_info("/x/f1", FileInfo("f1", 1, 10, id=7))
    assert t.get_file_id("/x/f1", 1, 10) == 7
    assert t.max_id == 7
    with pytest.raises(HyperspaceException):
        t.add_file_info("/x/f1", FileInfo("f1", 1, 10, id=8))


def test_log_entry_json_roundtrip():
    entry = make_entry()
    d = entry.to_dict()
    back = IndexLogEntry.from_dict(d)
    assert back == entry
    assert back.derived_dataset.indexed_columns == ["k"]
    assert back.derived_dataset.num_buckets == 8
    assert back.relation.root_paths == ["/data/t"]
    assert back.source_files_size_in_bytes == 300


def test_copy_with_update_records_delta():
    entry = make_entry()
    appended = Content.from_leaf_files([("/data/t/part-2.parquet", 50, 3000)])
    deleted = Content.from_leaf_files([("/data/t/part-0.parquet", 100, 1000)])
    fp = LogicalPlanFingerprint([Signature("file", "newsig")])
    updated = entry.copy_with_update(appended, deleted, fp)
    # original untouched
    assert entry.relation.update is None
    files = updated.source_file_info_set()
    assert "/data/t/part-2.parquet" in files
    assert "/data/t/part-0.parquet" not in files
    assert "/data/t/part-1.parquet" in files
    assert updated.fingerprint.signatures[0].value == "newsig"
    # roundtrip preserves update
    back = IndexLogEntry.from_dict(updated.to_dict())
    assert back.source_file_info_set().keys() == files.keys()


def test_tags_are_per_plan_and_not_serialized():
    entry = make_entry()
    entry.set_tag("plan1", "HYBRIDSCAN_REQUIRED", True)
    assert entry.get_tag("plan1", "HYBRIDSCAN_REQUIRED") is True
    assert entry.get_tag("plan2", "HYBRIDSCAN_REQUIRED") is None
    back = IndexLogEntry.from_dict(entry.to_dict())
    assert back.get_tag("plan1", "HYBRIDSCAN_REQUIRED") is None


def test_index_data_dir_id():
    entry = make_entry()
    assert entry.index_data_dir_id() == 0


def test_scheme_qualified_paths_roundtrip():
    c = Content.from_leaf_files(
        [("gs://bucket/data/f1.parquet", 5, 1), ("gs://bucket/data/sub/f2.parquet", 6, 2)]
    )
    assert sorted(c.files) == [
        "gs://bucket/data/f1.parquet",
        "gs://bucket/data/sub/f2.parquet",
    ]
    back = Content.from_dict(c.to_dict())
    assert sorted(back.files) == sorted(c.files)
