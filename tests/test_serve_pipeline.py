"""Pipelined join serve path (docs/serve-pipeline.md).

Differential doctrine: the pipelined serve (concurrent sides, per-bucket
scan/prepare overlap, off-critical-path hybrid delta) must return
BIT-IDENTICAL results to the sequential path — same rows, same order,
same string re-verification, same lineage handling — and the overlap
must be real (proven with an injected slow reader), not just plumbing.
"""

import dataclasses
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.io.columnar import ColumnarBatch


@pytest.fixture
def s1(session_factory):
    """Single-device session (the pipelined host serve path; the mesh8
    device-match matrix is covered by test_device_join_paths)."""
    return session_factory(1)


def _tables(tmp_path, n=40_000, n_orders=5_000, n_files=4):
    rng = np.random.default_rng(17)
    idir, odir = tmp_path / "items", tmp_path / "orders"
    idir.mkdir()
    odir.mkdir()
    items = pa.table(
        {
            "k": rng.integers(0, n_orders, n).astype(np.int64),
            "q": rng.integers(1, 51, n).astype(np.int64),
            "price": rng.normal(100.0, 10.0, n),
            "tag": pa.array(
                rng.choice(["alpha", "beta", "gamma", "delta"], n)
            ),
        }
    )
    orders = pa.table(
        {
            "ok": np.arange(n_orders, dtype=np.int64),
            "cust": rng.integers(0, 500, n_orders).astype(np.int64),
        }
    )
    for i in range(n_files):
        lo, hi = i * n // n_files, (i + 1) * n // n_files
        pq.write_table(items.slice(lo, hi - lo), str(idir / f"p{i}.parquet"))
        lo = i * n_orders // n_files
        hi = (i + 1) * n_orders // n_files
        pq.write_table(orders.slice(lo, hi - lo), str(odir / f"p{i}.parquet"))
    return str(idir), str(odir)


def _indexed_session(s, idir, odir):
    hs = Hyperspace(s)
    items = s.read.parquet(idir)
    orders = s.read.parquet(odir)
    hs.create_index(items, CoveringIndexConfig("i1", ["k"], ["q", "price", "tag"]))
    hs.create_index(orders, CoveringIndexConfig("o1", ["ok"], ["cust"]))
    s.enable_hyperspace()
    return hs, items, orders


def _join(s, orders, items):
    return (
        orders.join(items, on=orders["ok"] == items["k"])
        .select("ok", "cust", "q", "price", "tag")
        .collect()
    )


class TestPipelineBitIdentity:
    def test_join_identical_with_pipeline_on_and_off(self, s1, tmp_path):
        idir, odir = _tables(tmp_path)
        _, items, orders = _indexed_session(s1, idir, odir)
        plan = orders.join(items, on=orders["ok"] == items["k"]).explain()
        assert plan.count("Hyperspace(Type: CI") == 2, plan
        r_pipe = _join(s1, orders, items)
        s1.conf.set(C.SERVE_PIPELINE_ENABLED, False)
        r_seq = _join(s1, orders, items)
        assert r_pipe.equals(r_seq)  # rows AND order

    def test_hybrid_append_identical_and_string_verified(self, s1, tmp_path):
        idir, odir = _tables(tmp_path)
        _, items, orders = _indexed_session(s1, idir, odir)
        rng = np.random.default_rng(3)
        extra = pa.table(
            {
                "k": rng.integers(0, 5_000, 3_000).astype(np.int64),
                "q": np.full(3_000, 7, dtype=np.int64),
                "price": np.full(3_000, 1.0),
                "tag": pa.array(np.full(3_000, "omega")),
            }
        )
        pq.write_table(extra, idir + "/appended.parquet")
        s1.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        s1.index_manager.clear_cache()
        items2 = s1.read.parquet(idir)
        plan = orders.join(items2, on=orders["ok"] == items2["k"]).explain()
        assert plan.count("Hyperspace(Type: CI") == 2, plan
        r_pipe = _join(s1, orders, items2)
        s1.conf.set(C.SERVE_PIPELINE_ENABLED, False)
        r_seq = _join(s1, orders, items2)
        assert r_pipe.equals(r_seq)
        # the string payload column rode through dictionary concat on
        # both paths; the appended rows must be present
        assert "omega" in set(r_pipe.column("tag").to_pylist())

    def test_string_key_join_identical(self, s1, tmp_path):
        """String JOIN keys force the murmur-collision re-verify leg of
        _verify_keys on both paths."""
        rng = np.random.default_rng(7)
        idir, odir = tmp_path / "si", tmp_path / "so"
        idir.mkdir()
        odir.mkdir()
        keys = [f"user-{i}" for i in range(500)]
        left = pa.table(
            {
                "name": pa.array(rng.choice(keys, 20_000)),
                "v": rng.integers(0, 100, 20_000).astype(np.int64),
            }
        )
        right = pa.table(
            {"uname": pa.array(keys), "score": rng.normal(0, 1, len(keys))}
        )
        for i in range(2):
            pq.write_table(
                left.slice(i * 10_000, 10_000), str(idir / f"p{i}.parquet")
            )
            pq.write_table(
                right.slice(i * 250, 250), str(odir / f"p{i}.parquet")
            )
        hs = Hyperspace(s1)
        ldf, rdf = s1.read.parquet(str(idir)), s1.read.parquet(str(odir))
        hs.create_index(ldf, CoveringIndexConfig("si", ["name"], ["v"]))
        hs.create_index(rdf, CoveringIndexConfig("so", ["uname"], ["score"]))
        s1.enable_hyperspace()

        def q():
            return (
                ldf.join(rdf, on=ldf["name"] == rdf["uname"])
                .select("name", "v", "score")
                .collect()
            )

        r_pipe = q()
        s1.conf.set(C.SERVE_PIPELINE_ENABLED, False)
        assert q().equals(r_pipe)

    def test_delete_compensation_falls_back_and_matches(self, s1, tmp_path):
        """Hybrid DELETE compensation (lineage NOT-IN) breaks the clean
        shape: the pipelined gate must fall back to the sequential path
        — never a wrong answer, never a crash."""
        import os

        idir, odir = _tables(tmp_path)
        s1.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        _, items, orders = _indexed_session(s1, idir, odir)
        os.unlink(idir + "/p3.parquet")  # delete a source file
        s1.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        s1.conf.set(C.INDEX_HYBRID_SCAN_MAX_DELETED_RATIO, 1.0)
        s1.index_manager.clear_cache()
        items2 = s1.read.parquet(idir)
        r_pipe = _join(s1, orders, items2)
        s1.conf.set(C.SERVE_PIPELINE_ENABLED, False)
        r_seq = _join(s1, orders, items2)
        assert r_pipe.equals(r_seq)


class TestPreparePipelinedUnit:
    """prepare_join_side_pipelined vs prepare_join_side over the same
    batches — every PreparedJoinSide field bit-identical, including the
    string-dictionary concat, null masks and per-bucket sortedness."""

    def _random_buckets(self, rng, sorted_buckets):
        batches = {}
        for b in range(5):
            n = int(rng.integers(0, 2_000))
            keys = rng.integers(-50, 50, n).astype(np.int64)
            if sorted_buckets:
                keys = np.sort(keys)
            mask = rng.random(n) < 0.05
            arr = pa.array(
                np.where(mask, 0, keys), mask=mask, type=pa.int64()
            )
            tags = pa.array(rng.choice(["x", "y", "z"], n))
            batches[b] = ColumnarBatch.from_arrow(
                pa.table({"k": arr, "tag": tags})
            )
        return batches

    @pytest.mark.parametrize("sorted_buckets", [True, False])
    def test_fields_identical(self, sorted_buckets):
        from hyperspace_tpu.execution.join_exec import (
            prepare_join_side,
            prepare_join_side_pipelined,
        )

        rng = np.random.default_rng(13)
        batches = self._random_buckets(rng, sorted_buckets)
        seq = prepare_join_side(batches, ["k"])
        pipe = prepare_join_side_pipelined(
            [(b, (lambda bb=bb: bb)) for b, bb in sorted(batches.items())],
            ["k"],
        )
        assert pipe.buckets == seq.buckets
        np.testing.assert_array_equal(pipe.sizes, seq.sizes)
        np.testing.assert_array_equal(pipe.offs, seq.offs)
        np.testing.assert_array_equal(pipe.reps, seq.reps)
        np.testing.assert_array_equal(pipe.combined, seq.combined)
        assert (pipe.nulls is None) == (seq.nulls is None)
        if pipe.nulls is not None:
            np.testing.assert_array_equal(pipe.nulls, seq.nulls)
        assert pipe.sorted_buckets == seq.sorted_buckets
        assert pipe.batch.to_arrow().equals(seq.batch.to_arrow())

    def test_empty_stream_returns_none(self):
        from hyperspace_tpu.execution.join_exec import (
            prepare_join_side_pipelined,
        )

        assert prepare_join_side_pipelined([], ["k"]) is None


class TestScanPrepareOverlap:
    def test_slow_reader_overlaps_prepare(self, s1, tmp_path, monkeypatch):
        """Injected slow reader: scan of bucket i+1 must still be in
        flight when prepare of bucket i starts (the pipelined serve's
        core claim), and the result must equal the sequential path's."""
        from hyperspace_tpu.execution import executor as ex

        idir, odir = _tables(tmp_path)
        _, items, orders = _indexed_session(s1, idir, odir)
        r_seq_holder = {}
        s1.conf.set(C.SERVE_PIPELINE_ENABLED, False)
        r_seq_holder["r"] = _join(s1, orders, items)
        s1.conf.set(C.SERVE_PIPELINE_ENABLED, True)

        events = []
        ev_lock = threading.Lock()
        real_read = ex.pio.read_table

        def slow_read(paths, *a, **k):
            t0 = time.perf_counter()
            time.sleep(0.15)
            out = real_read(paths, *a, **k)
            with ev_lock:
                events.append(("scan", t0, time.perf_counter()))
            return out

        import hyperspace_tpu.execution.join_exec as je

        real_prepare = je.prepare_join_side_pipelined

        def traced_prepare(items_stream, key_cols, **kw):
            def trace(fetch):
                def run():
                    batch = fetch()
                    with ev_lock:
                        events.append(
                            ("prep_start", time.perf_counter(), None)
                        )
                    return batch

                return run

            return real_prepare(
                [(b, trace(f)) for b, f in items_stream], key_cols, **kw
            )

        monkeypatch.setattr(ex.pio, "read_table", slow_read)
        monkeypatch.setattr(
            je, "prepare_join_side_pipelined", traced_prepare
        )
        r_pipe = _join(s1, orders, items)
        assert r_pipe.equals(r_seq_holder["r"])
        scans = [e for e in events if e[0] == "scan"]
        preps = [e for e in events if e[0] == "prep_start"]
        assert len(scans) >= 8 and preps, events
        # overlap: some bucket's prepare began while a later-finishing
        # scan was still running
        last_scan_end = max(e[2] for e in scans)
        first_prep = min(e[1] for e in preps)
        assert first_prep < last_scan_end, (
            "no scan/prepare overlap: first prepare at "
            f"{first_prep}, last scan ended {last_scan_end}"
        )
        # and the scans themselves overlapped (read-ahead, not serial)
        scans_sorted = sorted(scans, key=lambda e: e[1])
        overlapping = any(
            scans_sorted[i + 1][1] < scans_sorted[i][2]
            for i in range(len(scans_sorted) - 1)
        )
        assert overlapping, "bucket reads ran strictly serially"


class TestDeltaCache:
    def test_delta_entry_cached_and_reused(self, s1, tmp_path, monkeypatch):
        """With serve-server mode on, the prepared hybrid delta is cached
        by file fingerprint: evicting every OTHER entry kind must not
        cause the appended file to be re-read."""
        from hyperspace_tpu.execution import executor as ex

        idir, odir = _tables(tmp_path)
        _, items, orders = _indexed_session(s1, idir, odir)
        rng = np.random.default_rng(9)
        extra = pa.table(
            {
                "k": rng.integers(0, 5_000, 2_000).astype(np.int64),
                "q": np.full(2_000, 9, dtype=np.int64),
                "price": np.full(2_000, 2.0),
                "tag": pa.array(np.full(2_000, "late")),
            }
        )
        appended_path = idir + "/appended.parquet"
        pq.write_table(extra, appended_path)
        s1.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        s1.conf.set(C.SERVE_CACHE_ENABLED, True)
        s1.index_manager.clear_cache()
        items2 = s1.read.parquet(idir)
        baseline = _join(s1, orders, items2)
        cache = s1.serve_cache
        kinds = {k[0] for k in cache._entries}
        assert "delta" in kinds, kinds
        # drop everything except the delta; count appended-file reads
        for kind in ("joinside", "bucketed", "scan"):
            cache.evict_kind(kind)
        reads = []
        real_read = ex.pio.read_table

        def counting_read(paths, *a, **k):
            reads.extend(
                p for p in paths if str(p).endswith("appended.parquet")
            )
            return real_read(paths, *a, **k)

        monkeypatch.setattr(ex.pio, "read_table", counting_read)
        again = _join(s1, orders, items2)
        assert again.equals(baseline)
        assert not reads, "appended delta re-read despite cached entry"
        # appending ANOTHER file re-keys the delta entry (fingerprint)
        monkeypatch.undo()
        pq.write_table(extra, idir + "/appended2.parquet")
        s1.index_manager.clear_cache()
        items3 = s1.read.parquet(idir)
        r3 = _join(s1, orders, items3)
        assert r3.num_rows == baseline.num_rows + 2_000

    def test_evict_kind(self):
        from hyperspace_tpu.execution.serve_cache import ServeCache

        c = ServeCache(max_bytes=1000)
        c.put(("delta", 1), "a", 10)
        c.put(("joinside", 1), "b", 10)
        c.put(("joinside", 2), "c", 10)
        assert c.evict_kind("joinside") == 2
        assert c.get(("delta", 1)) == "a"
        assert c.get(("joinside", 1)) is None
        assert c.resident_bytes == 10
