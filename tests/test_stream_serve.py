"""Streaming per-bucket join serve (docs/out-of-core.md).

Differential doctrine, three ways: the streaming serve (per-bucket
waves packed under ``hyperspace.serve.stream.maxBytes``, read →
prepare → match → release) must return BIT-IDENTICAL results to the
materializing path, which must itself match the unindexed answer —
across int64/float64/string payloads, string JOIN keys, hybrid-scan
appended deltas and lineage delete compensation. The wave machinery is
proven real with the ``executor.last_stream_stats`` telemetry (a small
budget must produce many waves), not just plumbing.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.io.columnar import ColumnarBatch


@pytest.fixture
def s1(session_factory):
    return session_factory(1)


def sorted_table(t: pa.Table) -> pa.Table:
    return t.sort_by([(c, "ascending") for c in t.column_names])


def _tables(tmp_path, n=40_000, n_orders=5_000, n_files=4):
    rng = np.random.default_rng(17)
    idir, odir = tmp_path / "items", tmp_path / "orders"
    idir.mkdir()
    odir.mkdir()
    items = pa.table(
        {
            "k": rng.integers(0, n_orders, n).astype(np.int64),
            "q": rng.integers(1, 51, n).astype(np.int64),
            "price": rng.normal(100.0, 10.0, n),
            "tag": pa.array(
                rng.choice(["alpha", "beta", "gamma", "delta"], n)
            ),
        }
    )
    orders = pa.table(
        {
            "ok": np.arange(n_orders, dtype=np.int64),
            "cust": rng.integers(0, 500, n_orders).astype(np.int64),
        }
    )
    for i in range(n_files):
        lo, hi = i * n // n_files, (i + 1) * n // n_files
        pq.write_table(items.slice(lo, hi - lo), str(idir / f"p{i}.parquet"))
        lo = i * n_orders // n_files
        hi = (i + 1) * n_orders // n_files
        pq.write_table(orders.slice(lo, hi - lo), str(odir / f"p{i}.parquet"))
    return str(idir), str(odir)


def _indexed_session(s, idir, odir):
    hs = Hyperspace(s)
    items = s.read.parquet(idir)
    orders = s.read.parquet(odir)
    hs.create_index(
        items, CoveringIndexConfig("i1", ["k"], ["q", "price", "tag"])
    )
    hs.create_index(orders, CoveringIndexConfig("o1", ["ok"], ["cust"]))
    s.enable_hyperspace()
    return hs, items, orders


def _join(s, orders, items):
    return (
        orders.join(items, on=orders["ok"] == items["k"])
        .select("ok", "cust", "q", "price", "tag")
        .collect()
    )


class TestStreamBitIdentity:
    """stream on ≡ stream off ≡ unindexed — the three-way differential."""

    def test_multiwave_three_way_differential(self, s1, tmp_path):
        from hyperspace_tpu.execution import executor as ex

        idir, odir = _tables(tmp_path)
        _, items, orders = _indexed_session(s1, idir, odir)
        s1.conf.set(C.SERVE_STREAM_ENABLED, True)
        s1.conf.set(C.SERVE_STREAM_MAX_BYTES, 64_000)  # force many waves
        r_stream = _join(s1, orders, items)
        stats = dict(ex.last_stream_stats)
        assert stats.get("stream_waves", 0) > 1, stats
        s1.conf.set(C.SERVE_STREAM_ENABLED, False)
        r_mat = _join(s1, orders, items)
        assert r_stream.equals(r_mat)  # rows AND order
        s1.disable_hyperspace()
        r_plain = _join(s1, orders, items)
        assert sorted_table(r_stream).equals(sorted_table(r_plain))

    def test_single_wave_identical(self, s1, tmp_path):
        idir, odir = _tables(tmp_path)
        _, items, orders = _indexed_session(s1, idir, odir)
        s1.conf.set(C.SERVE_STREAM_ENABLED, True)
        s1.conf.set(C.SERVE_STREAM_MAX_BYTES, 1 << 30)  # one wave
        r_stream = _join(s1, orders, items)
        s1.conf.set(C.SERVE_STREAM_ENABLED, False)
        assert r_stream.equals(_join(s1, orders, items))

    def test_mmap_reads_identical(self, s1, tmp_path):
        """Streaming over memory-mapped parquet reads
        (``hyperspace.io.mmap.enabled``) changes the buffers' backing,
        never the bytes."""
        idir, odir = _tables(tmp_path)
        _, items, orders = _indexed_session(s1, idir, odir)
        s1.conf.set(C.SERVE_STREAM_ENABLED, True)
        s1.conf.set(C.SERVE_STREAM_MAX_BYTES, 64_000)
        s1.conf.set(C.IO_MMAP_ENABLED, True)
        r_mmap = _join(s1, orders, items)
        s1.conf.set(C.IO_MMAP_ENABLED, False)
        s1.conf.set(C.SERVE_STREAM_ENABLED, False)
        assert r_mmap.equals(_join(s1, orders, items))

    def test_string_key_join_identical(self, s1, tmp_path):
        """String JOIN keys force the murmur-collision re-verify leg on
        every wave."""
        rng = np.random.default_rng(7)
        idir, odir = tmp_path / "si", tmp_path / "so"
        idir.mkdir()
        odir.mkdir()
        keys = [f"user-{i}" for i in range(500)]
        left = pa.table(
            {
                "name": pa.array(rng.choice(keys, 20_000)),
                "v": rng.integers(0, 100, 20_000).astype(np.int64),
            }
        )
        right = pa.table(
            {"uname": pa.array(keys), "score": rng.normal(0, 1, len(keys))}
        )
        for i in range(2):
            pq.write_table(
                left.slice(i * 10_000, 10_000), str(idir / f"p{i}.parquet")
            )
            pq.write_table(
                right.slice(i * 250, 250), str(odir / f"p{i}.parquet")
            )
        hs = Hyperspace(s1)
        ldf, rdf = s1.read.parquet(str(idir)), s1.read.parquet(str(odir))
        hs.create_index(ldf, CoveringIndexConfig("si", ["name"], ["v"]))
        hs.create_index(rdf, CoveringIndexConfig("so", ["uname"], ["score"]))
        s1.enable_hyperspace()

        def q():
            return (
                ldf.join(rdf, on=ldf["name"] == rdf["uname"])
                .select("name", "v", "score")
                .collect()
            )

        s1.conf.set(C.SERVE_STREAM_ENABLED, True)
        s1.conf.set(C.SERVE_STREAM_MAX_BYTES, 64_000)
        r_stream = q()
        s1.conf.set(C.SERVE_STREAM_ENABLED, False)
        assert r_stream.equals(q())

    def test_hybrid_append_identical(self, s1, tmp_path):
        """Appended delta files (hybrid scan) merge into each wave's
        bucket exactly as the materializing Union path merges them."""
        idir, odir = _tables(tmp_path)
        _, items, orders = _indexed_session(s1, idir, odir)
        rng = np.random.default_rng(3)
        extra = pa.table(
            {
                "k": rng.integers(0, 5_000, 3_000).astype(np.int64),
                "q": np.full(3_000, 7, dtype=np.int64),
                "price": np.full(3_000, 1.0),
                "tag": pa.array(np.full(3_000, "omega")),
            }
        )
        pq.write_table(extra, idir + "/appended.parquet")
        s1.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        s1.index_manager.clear_cache()
        items2 = s1.read.parquet(idir)
        s1.conf.set(C.SERVE_STREAM_ENABLED, True)
        s1.conf.set(C.SERVE_STREAM_MAX_BYTES, 64_000)
        r_stream = _join(s1, orders, items2)
        s1.conf.set(C.SERVE_STREAM_ENABLED, False)
        r_mat = _join(s1, orders, items2)
        assert r_stream.equals(r_mat)
        assert "omega" in set(r_stream.column("tag").to_pylist())

    def test_delete_compensation_falls_back_and_matches(self, s1, tmp_path):
        """Lineage delete compensation (NOT-IN over deleted files)
        breaks the streamable shape: the probe must decline and the
        fallback must serve the right answer — never a wrong one,
        never a crash."""
        idir, odir = _tables(tmp_path)
        s1.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        _, items, orders = _indexed_session(s1, idir, odir)
        os.unlink(idir + "/p3.parquet")
        s1.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        s1.conf.set(C.INDEX_HYBRID_SCAN_MAX_DELETED_RATIO, 1.0)
        s1.index_manager.clear_cache()
        items2 = s1.read.parquet(idir)
        s1.conf.set(C.SERVE_STREAM_ENABLED, True)
        s1.conf.set(C.SERVE_STREAM_MAX_BYTES, 64_000)
        r_stream = _join(s1, orders, items2)
        s1.conf.set(C.SERVE_STREAM_ENABLED, False)
        assert r_stream.equals(_join(s1, orders, items2))


class TestStreamWaves:
    def test_wave_telemetry_and_stage_span(self, s1, tmp_path):
        """A small budget must pack many waves, the bucket count must
        cover every common bucket exactly once, and the stream_wave
        stage must land in the serve breakdown (the taxonomy the
        querylog and bench gates key on)."""
        from hyperspace_tpu.execution import executor as ex
        from hyperspace_tpu.execution.join_exec import last_serve_breakdown

        idir, odir = _tables(tmp_path)
        _, items, orders = _indexed_session(s1, idir, odir)
        s1.conf.set(C.SERVE_STREAM_ENABLED, True)
        s1.conf.set(C.SERVE_STREAM_MAX_BYTES, 64_000)
        small = _join(s1, orders, items)
        many = dict(ex.last_stream_stats)
        bd = dict(last_serve_breakdown)
        s1.conf.set(C.SERVE_STREAM_MAX_BYTES, 1 << 30)
        big = _join(s1, orders, items)
        one = dict(ex.last_stream_stats)
        assert small.equals(big)
        assert many["stream_waves"] > one["stream_waves"] == 1
        # waves partition the common buckets: same total either way
        assert many["stream_buckets"] == one["stream_buckets"]
        assert bd.get("stream_wave", 0) > 0, bd

    def test_oversized_bucket_runs_alone(self, s1, tmp_path):
        """A budget smaller than every bucket degenerates to one bucket
        per wave — correctness never depends on the estimate."""
        from hyperspace_tpu.execution import executor as ex

        idir, odir = _tables(tmp_path)
        _, items, orders = _indexed_session(s1, idir, odir)
        s1.conf.set(C.SERVE_STREAM_ENABLED, True)
        s1.conf.set(C.SERVE_STREAM_MAX_BYTES, 1)
        r = _join(s1, orders, items)
        stats = dict(ex.last_stream_stats)
        assert stats["stream_waves"] == stats["stream_buckets"]
        s1.conf.set(C.SERVE_STREAM_ENABLED, False)
        assert r.equals(_join(s1, orders, items))


class TestPrepareContiguousUnit:
    """prepare_join_side_contiguous vs prepare_join_side over the same
    rows — every PreparedJoinSide field bit-identical. The contiguous
    twin is the streaming wave's zero-concat prepare: its input batch IS
    the concatenation the per-bucket path would have built."""

    def _bucketed(self, rng, sorted_keys, with_nulls=True):
        batches = {}
        for b in range(5):
            n = int(rng.integers(1, 2_000))
            keys = rng.integers(-50, 50, n).astype(np.int64)
            if sorted_keys:
                keys = np.sort(keys)
            mask = rng.random(n) < (0.05 if with_nulls else 0.0)
            arr = pa.array(
                np.where(mask, 0, keys), mask=mask, type=pa.int64()
            )
            tags = pa.array(rng.choice(["x", "y", "z"], n))
            batches[b] = ColumnarBatch.from_arrow(
                pa.table({"k": arr, "tag": tags})
            )
        return batches

    @pytest.mark.parametrize("sorted_keys", [True, False])
    @pytest.mark.parametrize("with_nulls", [True, False])
    def test_fields_identical(self, sorted_keys, with_nulls):
        from hyperspace_tpu.execution.join_exec import (
            prepare_join_side,
            prepare_join_side_contiguous,
        )

        rng = np.random.default_rng(13)
        batches = self._bucketed(rng, sorted_keys, with_nulls)
        seq = prepare_join_side(batches, ["k"])
        order = sorted(batches)
        contig = prepare_join_side_contiguous(
            ColumnarBatch.concat([batches[b] for b in order]),
            tuple(order),
            [batches[b].num_rows for b in order],
            ["k"],
        )
        assert contig.buckets == seq.buckets
        np.testing.assert_array_equal(contig.sizes, seq.sizes)
        np.testing.assert_array_equal(contig.offs, seq.offs)
        np.testing.assert_array_equal(contig.reps, seq.reps)
        np.testing.assert_array_equal(contig.combined, seq.combined)
        assert (contig.nulls is None) == (seq.nulls is None)
        if contig.nulls is not None:
            np.testing.assert_array_equal(contig.nulls, seq.nulls)
        assert contig.sorted_buckets == seq.sorted_buckets
        assert contig.batch.to_arrow().equals(seq.batch.to_arrow())

    def test_empty_wave_returns_none(self):
        from hyperspace_tpu.execution.join_exec import (
            prepare_join_side_contiguous,
        )

        empty = ColumnarBatch.from_arrow(
            pa.table({"k": pa.array([], type=pa.int64())})
        )
        assert prepare_join_side_contiguous(empty, (), [], ["k"]) is None
