"""Device-branch coverage for the co-bucketed serve join.

The host presorted path dominates single-device serves, so the DEVICE
branches — the vmapped/sharded match kernel (`ops/join.bucketed_match_ranges`
via `join_exec._device_match`), bucket-dimension padding for uneven
mesh division, and sentinel handling under the device path — get
dedicated differential coverage here (round-4 review: device serve
coverage was thinner than build coverage).
"""

import numpy as np
import pyarrow as pa
import pytest

from hyperspace_tpu.execution.join_exec import (
    co_bucketed_join,
    co_bucketed_join_prepared,
    inner_join,
    prepare_join_side,
)
from hyperspace_tpu.io.columnar import ColumnarBatch
from hyperspace_tpu.parallel.mesh import default_mesh


def _mesh8():
    import jax

    return default_mesh(jax.devices()[:8])


def _batch(**cols):
    return ColumnarBatch.from_arrow(pa.table(cols))


def _rand_buckets(rng, n_buckets, rows_per_bucket, keys=1, null_frac=0.0):
    """Per-bucket batches with UNSORTED keys (forces the general path)."""
    out = {}
    for b in range(n_buckets):
        n = rows_per_bucket
        cols = {}
        for k in range(keys):
            v = rng.integers(0, 40, n).astype(np.int64)
            if null_frac:
                mask = rng.random(n) < null_frac
                arr = pa.array(
                    [None if m else int(x) for x, m in zip(v, mask)],
                    type=pa.int64(),
                )
            else:
                arr = pa.array(v, type=pa.int64())
            cols[f"k{k}"] = arr
        cols["payload"] = pa.array(rng.normal(0, 1, n))
        out[b] = ColumnarBatch.from_arrow(pa.table(cols))
    return out


def _rename(bs, mapping):
    return {
        b: ColumnarBatch(
            {mapping.get(n, n): c for n, c in batch.columns.items()}
        )
        for b, batch in bs.items()
    }


def _ground_truth(lbs, rbs, on):
    """Oracle: per-bucket inner_join (the independently-tested generic
    path), concatenated."""
    parts = []
    for b in sorted(set(lbs) & set(rbs)):
        j = inner_join(lbs[b], rbs[b], on)
        if j.num_rows:
            parts.append(j)
    if not parts:
        return None
    return ColumnarBatch.concat(parts)


def _assert_same(got, want):
    if want is None:
        assert got is None or got.num_rows == 0
        return
    gt, wt = got.to_arrow(), want.to_arrow()
    key = [(c, "ascending") for c in gt.column_names]
    assert gt.sort_by(key).equals(wt.sort_by(key))


class TestDeviceMatchPaths:
    def test_sharded_device_match_unsorted_buckets(self):
        rng = np.random.default_rng(0)
        lbs = _rand_buckets(rng, 8, 200)
        rbs = _rename(_rand_buckets(rng, 8, 150), {"k0": "j0", "payload": "rp"})
        on = [("k0", "j0")]
        got = co_bucketed_join(lbs, rbs, on, mesh=_mesh8(), device_min_rows=1)
        _assert_same(got, _ground_truth(lbs, rbs, on))

    def test_bucket_count_not_divisible_by_mesh(self):
        # 6 buckets over an 8-device mesh: the device path pads the
        # bucket dimension so shard_map divides evenly
        rng = np.random.default_rng(1)
        lbs = _rand_buckets(rng, 6, 100)
        rbs = _rename(_rand_buckets(rng, 6, 90), {"k0": "j0", "payload": "rp"})
        on = [("k0", "j0")]
        got = co_bucketed_join(lbs, rbs, on, mesh=_mesh8(), device_min_rows=1)
        _assert_same(got, _ground_truth(lbs, rbs, on))

    def test_multi_key_device_match_verifies_collisions(self):
        rng = np.random.default_rng(2)
        lbs = _rand_buckets(rng, 8, 120, keys=2)
        rbs = _rename(
            _rand_buckets(rng, 8, 110, keys=2),
            {"k0": "j0", "k1": "j1", "payload": "rp"},
        )
        on = [("k0", "j0"), ("k1", "j1")]
        got = co_bucketed_join(lbs, rbs, on, mesh=_mesh8(), device_min_rows=1)
        _assert_same(got, _ground_truth(lbs, rbs, on))

    def test_null_keys_through_device_path(self):
        rng = np.random.default_rng(3)
        lbs = _rand_buckets(rng, 8, 80, null_frac=0.15)
        rbs = _rename(
            _rand_buckets(rng, 8, 70, null_frac=0.15),
            {"k0": "j0", "payload": "rp"},
        )
        on = [("k0", "j0")]
        got = co_bucketed_join(lbs, rbs, on, mesh=_mesh8(), device_min_rows=1)
        _assert_same(got, _ground_truth(lbs, rbs, on))

    def test_forced_device_on_single_device(self):
        # mesh=None + device_min_rows=1 exercises the jit-vmapped (not
        # sharded) device kernel with unsorted buckets
        rng = np.random.default_rng(4)
        lbs = _rand_buckets(rng, 4, 60)
        rbs = _rename(_rand_buckets(rng, 4, 50), {"k0": "j0", "payload": "rp"})
        on = [("k0", "j0")]
        got = co_bucketed_join(lbs, rbs, on, mesh=None, device_min_rows=1)
        _assert_same(got, _ground_truth(lbs, rbs, on))

    def test_prepared_sides_reused_across_device_serves(self):
        # the serve cache's contract: one PreparedJoinSide serves many
        # queries — the device path must not mutate it
        rng = np.random.default_rng(5)
        lbs = _rand_buckets(rng, 8, 100)
        rbs = _rename(_rand_buckets(rng, 8, 90), {"k0": "j0", "payload": "rp"})
        on = [("k0", "j0")]
        lp = prepare_join_side(lbs, ["k0"])
        rp = prepare_join_side(rbs, ["j0"])
        mesh = _mesh8()
        first = co_bucketed_join_prepared(lp, rp, on, mesh, 1)
        combined_before = lp.combined.copy()
        second = co_bucketed_join_prepared(lp, rp, on, mesh, 1)
        assert np.array_equal(lp.combined, combined_before)
        _assert_same(second, first)


class TestDeviceJoinEndToEnd:
    def test_forced_device_join_full_query(self, session_factory, tmp_path):
        """deviceJoinMinRows=1 routes a full indexed-join query through
        the device kernel at mesh 8; answer matches the host default."""
        import pyarrow.parquet as pq

        from hyperspace_tpu import constants as C
        from hyperspace_tpu.hyperspace import Hyperspace
        from hyperspace_tpu.indexes.covering import CoveringIndexConfig

        session = session_factory(8)
        rng = np.random.default_rng(6)
        d1, d2 = tmp_path / "l", tmp_path / "r"
        d1.mkdir(), d2.mkdir()
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(rng.integers(0, 200, 4000), pa.int64()),
                    "v": pa.array(rng.normal(0, 1, 4000)),
                }
            ),
            d1 / "a.parquet",
        )
        pq.write_table(
            pa.table(
                {
                    "j": pa.array(np.arange(200), pa.int64()),
                    "w": pa.array(rng.normal(0, 1, 200)),
                }
            ),
            d2 / "a.parquet",
        )
        hs = Hyperspace(session)
        dl = session.read.parquet(str(d1))
        dr = session.read.parquet(str(d2))
        hs.create_index(dl, CoveringIndexConfig("l8", ["k"], ["v"]))
        hs.create_index(dr, CoveringIndexConfig("r8", ["j"], ["w"]))
        session.enable_hyperspace()
        q = lambda l: dr.join(l, on=dr["j"] == l["k"]).select("j", "w", "v")
        assert q(dl).explain().count("Hyperspace(Type: CI") == 2
        host = q(dl).collect()
        assert host.num_rows == 4000
        key = [(c, "ascending") for c in host.column_names]
        session.conf.set(C.EXECUTION_DEVICE_JOIN_MIN_ROWS, 1)
        dev = q(dl).collect()
        assert dev.sort_by(key).equals(host.sort_by(key))
        # clean index scans are presorted (host fast path even when the
        # device is forced); a hybrid-APPENDED tail is genuinely unsorted
        # and routes the whole serve through the sharded device kernel
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(rng.integers(0, 200, 300), pa.int64()),
                    "v": pa.array(rng.normal(0, 1, 300)),
                }
            ),
            d1 / "appended.parquet",
        )
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        session.index_manager.clear_cache()
        dl2 = session.read.parquet(str(d1))
        assert q(dl2).explain().count("Hyperspace(Type: CI") == 2
        dev_hybrid = q(dl2).collect()
        session.disable_hyperspace()
        base = q(dl2).collect()
        assert dev_hybrid.sort_by(key).equals(base.sort_by(key))
        assert dev_hybrid.num_rows == 4300
