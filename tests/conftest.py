"""Test fixtures.

Distribution is simulated on a virtual 8-device CPU mesh — the TPU
equivalent of the reference's ``local[4]`` Spark test sessions
(``SparkInvolvedSuite.scala:31-47``): set XLA_FLAGS before JAX import so
``jax.devices()`` reports 8 host devices.
"""

import os

# Must happen before any jax import anywhere in the test process. Force CPU
# even when the ambient environment points at a real TPU (JAX_PLATFORMS=axon)
# — tests simulate the mesh with 8 virtual host devices. The env var alone
# is not enough (a platform plugin pre-sets jax_platforms), so also override
# the config after import, before any backend is initialized.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _lock_witness_session():
    """When ``HS_LOCK_WITNESS=<path>`` is set, wrap every
    SHARED_STATE-registered lock for the whole test session and dump
    the observed acquisition edges + per-lock counts into the artifact
    at exit (merging across suites). ``hslint --witness <path>`` then
    cross-checks the runtime behavior against the static lock model —
    see scripts/bench_smoke.sh, docs/static-analysis.md."""
    path = os.environ.get("HS_LOCK_WITNESS")
    if not path:
        yield
        return
    from hyperspace_tpu.testing import lock_witness

    lock_witness.install()
    try:
        yield
    finally:
        lock_witness.dump(path)
        lock_witness.uninstall()


@pytest.fixture(scope="session", autouse=True)
def _residency_witness_session():
    """When ``HS_RESIDENCY_WITNESS=<path>`` is set, wrap every
    ALLOC_SITES-registered allocation site for the whole test session
    and dump the observed per-site peak bytes + call counts + process
    RSS high-water into the artifact at exit (merging across suites).
    ``hslint --witness <path>`` then cross-checks the runtime residency
    against the static bound model — see scripts/bench_smoke.sh,
    docs/static-analysis.md."""
    path = os.environ.get("HS_RESIDENCY_WITNESS")
    if not path:
        yield
        return
    from hyperspace_tpu.testing import residency_witness

    residency_witness.install()
    try:
        yield
    finally:
        residency_witness.dump(path)
        residency_witness.uninstall()


@pytest.fixture
def tmp_index_root(tmp_path):
    """Per-test index system path (HyperspaceSuite's per-suite systemPath)."""
    p = tmp_path / "indexes"
    p.mkdir()
    return str(p)


@pytest.fixture
def sample_parquet(tmp_path):
    """Small parquet dataset (reference SampleData.scala analogue)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    d = tmp_path / "sample"
    d.mkdir()
    for i in range(3):
        n = 100
        t = pa.table(
            {
                "date": pa.array(
                    [f"2017-09-{(j % 28) + 1:02d}" for j in range(n)]
                ),
                "rguid": pa.array([f"guid-{i}-{j}" for j in range(n)]),
                "clicks": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
                "query": pa.array(
                    [["ibraco", "facebook", "donde", "banana"][j % 4] for j in range(n)]
                ),
                "imprs": pa.array(rng.integers(0, 100, n), type=pa.int64()),
            }
        )
        pq.write_table(t, d / f"part-{i}.parquet")
    return str(d)


def _make_session(index_root, n_devices=None):
    from hyperspace_tpu.session import HyperspaceSession
    from hyperspace_tpu import constants as C

    devices = jax.devices()[:n_devices] if n_devices is not None else None
    s = HyperspaceSession(devices=devices)
    s.conf.set(C.INDEX_SYSTEM_PATH, index_root)
    # Small bucket count for tests (reference tests use 5 shuffle partitions)
    s.conf.set(C.INDEX_NUM_BUCKETS, 8)
    return s


@pytest.fixture(params=[1, 8], ids=["mesh1", "mesh8"])
def session(request, tmp_index_root):
    """Every session-driven test runs at mesh sizes 1 and 8 — the
    HybridScanSuite-style matrix (the reference specializes shared
    scenarios per environment; here the environment axis is the mesh)."""
    return _make_session(tmp_index_root, request.param)


@pytest.fixture
def session_factory(tmp_index_root):
    """Build sessions of chosen mesh size over the SAME index system path
    (cross-mesh layout-compat tests: build at one size, serve at another)."""
    return lambda n_devices: _make_session(tmp_index_root, n_devices)
