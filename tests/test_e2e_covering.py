"""End-to-end covering index tests: create → plan rewrite → serve.

Mirrors the reference's ``index/E2EHyperspaceRulesTest.scala`` pattern:
(a) the rewritten plan scans the index (Hyperspace relation in the plan
string), and (b) **query results with the index == results without**
(``checkAnswer``-style differential, `:76-120`).
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.constants import States
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def sorted_table(t: pa.Table) -> pa.Table:
    return t.sort_by([(c, "ascending") for c in t.column_names])


class TestCreateIndex:
    def test_create_and_list(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))
        listing = hs.indexes()
        assert listing.num_rows == 1
        assert listing.column("name").to_pylist() == ["idx1"]
        assert listing.column("state").to_pylist() == [States.ACTIVE]
        assert listing.column("indexedColumns").to_pylist() == ["clicks"]

    def test_create_writes_bucketed_sorted_files(
        self, session, hs, sample_parquet, tmp_index_root
    ):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))
        entry = session.index_manager.get_index_log_entry("idx1")
        files = entry.content.files
        assert files, "index has content files"
        from hyperspace_tpu.io.parquet import bucket_id_of_file

        total = 0
        for f in files:
            b = bucket_id_of_file(f)
            assert b is not None and 0 <= b < 8
            t = pq.read_table(f)
            total += t.num_rows
            clicks = t.column("clicks").to_pylist()
            assert clicks == sorted(clicks), "sorted within bucket"
        assert total == 300

    def test_create_duplicate_fails(self, session, hs, sample_parquet):
        from hyperspace_tpu.exceptions import HyperspaceException

        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"]))
        with pytest.raises(HyperspaceException, match="already exists"):
            hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"]))

    def test_create_case_insensitive_columns(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["CLICKS"], ["Query"]))
        entry = session.index_manager.get_index_log_entry("idx1")
        assert entry.derived_dataset.indexed_columns == ["clicks"]

    def test_create_unresolvable_column_fails(self, session, hs, sample_parquet):
        from hyperspace_tpu.exceptions import HyperspaceException

        df = session.read.parquet(sample_parquet)
        with pytest.raises(HyperspaceException, match="resolved"):
            hs.create_index(df, CoveringIndexConfig("idx1", ["nope"]))


class TestFilterIndexServe:
    def test_filter_query_uses_index_and_matches(
        self, session, hs, sample_parquet
    ):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))

        q = lambda d: d.filter(d["clicks"] >= 500).select("clicks", "query")

        session.disable_hyperspace()
        without = q(df).collect()
        session.enable_hyperspace()
        with_index = q(df).collect()
        plan = q(df).explain()
        assert "Hyperspace(Type: CI, Name: idx1" in plan
        assert sorted_table(with_index).equals(sorted_table(without))
        assert with_index.num_rows > 0

    def test_filter_not_rewritten_when_first_col_missing(
        self, session, hs, sample_parquet
    ):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))
        session.enable_hyperspace()
        # predicate on 'query' only: first indexed col (clicks) unconstrained
        plan = df.filter(df["query"] == "banana").select("query", "clicks").explain()
        assert "Hyperspace" not in plan

    def test_filter_not_rewritten_when_columns_uncovered(
        self, session, hs, sample_parquet
    ):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))
        session.enable_hyperspace()
        plan = (
            df.filter(df["clicks"] == 5).select("clicks", "imprs").explain()
        )  # imprs not covered
        assert "Hyperspace" not in plan

    def test_source_change_invalidates_index(
        self, session, hs, sample_parquet
    ):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))
        # append a new source file AFTER indexing
        t = pa.table(
            {
                "date": ["2018-01-01"] * 5,
                "rguid": [f"g{i}" for i in range(5)],
                "clicks": pa.array([9991, 9992, 9993, 9994, 9995], pa.int64()),
                "query": ["new"] * 5,
                "imprs": pa.array([1, 2, 3, 4, 5], pa.int64()),
            }
        )
        pq.write_table(t, os.path.join(sample_parquet, "part-new.parquet"))
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 9000).select("clicks", "query")
        plan = q(df2).explain()
        # hybrid scan disabled by default ⇒ stale index must NOT be used
        assert "Hyperspace" not in plan
        out = q(df2).collect()
        assert out.num_rows == 5  # fresh rows visible

    def test_rewrite_disabled_flag(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))
        session.enable_hyperspace()
        session.conf.set(C.HYPERSPACE_APPLY_ENABLED, False)
        plan = df.filter(df["clicks"] > 1).select("clicks").explain()
        assert "Hyperspace" not in plan

    def test_bucket_pruned_point_filter(self, session, hs, sample_parquet):
        """With useBucketSpec on, a point filter reads only the bucket
        file(s) the literal hashes to, and the answer is unchanged."""
        from hyperspace_tpu.execution.executor import _bucket_pruned_scan
        from hyperspace_tpu.plan.nodes import Filter, Project, Scan

        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))
        session.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
        session.enable_hyperspace()
        key = int(df.collect().column("clicks")[0].as_py())
        q = lambda d: d.filter(d["clicks"] == key).select("clicks", "query")
        optimized = session.optimize(q(df).logical_plan)
        # walk to the Filter->Scan and check pruning drops files
        node = optimized
        while not isinstance(node, Filter):
            node = node.child
        assert isinstance(node.child, Scan)
        assert node.child.relation.bucket_spec is not None
        pruned = _bucket_pruned_scan(node.child, node.condition)
        assert len(pruned.relation.files) < len(node.child.relation.files)
        # differential: pruned answer == unindexed answer
        session.disable_hyperspace()
        without = q(df).collect()
        session.enable_hyperspace()
        got = q(df).collect()
        assert sorted_table(got).equals(sorted_table(without))
        assert got.num_rows > 0

    def test_bucket_pruned_in_filter(self, session, hs, sample_parquet):
        """IN-list point filters prune to the union of the values' buckets."""
        from hyperspace_tpu.execution.executor import _bucket_pruned_scan
        from hyperspace_tpu.plan.nodes import Filter, Scan

        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx_s", ["query"], ["clicks"]))
        session.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
        session.enable_hyperspace()
        q = lambda d: d.filter(
            d["query"].isin("banana", "donde")
        ).select("query", "clicks")
        plan = q(df).explain()
        assert "Hyperspace(Type: CI, Name: idx_s" in plan
        optimized = session.optimize(q(df).logical_plan)
        node = optimized
        while not isinstance(node, Filter):
            node = node.child
        assert isinstance(node.child, Scan)
        pruned = _bucket_pruned_scan(node.child, node.condition)
        assert len(pruned.relation.files) < len(node.child.relation.files)
        session.disable_hyperspace()
        without = q(df).collect()
        session.enable_hyperspace()
        got = q(df).collect()
        assert sorted_table(got).equals(sorted_table(without))
        assert got.num_rows > 0

    def test_string_indexed_column(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx_s", ["query"], ["clicks"]))
        session.enable_hyperspace()
        q = lambda d: d.filter(d["query"] == "banana").select("query", "clicks")
        plan = q(df).explain()
        assert "Hyperspace(Type: CI, Name: idx_s" in plan
        session.disable_hyperspace()
        without = q(df).collect()
        session.enable_hyperspace()
        got = q(df).collect()
        assert sorted_table(got).equals(sorted_table(without))


class TestHybridScan:
    def test_appended_files_served_hybrid(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))
        # append AFTER indexing
        t = pa.table(
            {
                "date": ["2018-01-01"] * 3,
                "rguid": ["a", "b", "c"],
                "clicks": pa.array([700, 701, 702], pa.int64()),
                "query": ["hybrid"] * 3,
                "imprs": pa.array([1, 2, 3], pa.int64()),
            }
        )
        pq.write_table(t, os.path.join(sample_parquet, "part-extra.parquet"))
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] >= 500).select("clicks", "query")
        plan = q(df2).explain()
        assert "Hyperspace(Type: CI, Name: idx1" in plan
        assert "Union" in plan
        session.disable_hyperspace()
        without = q(df2).collect()
        session.enable_hyperspace()
        got = q(df2).collect()
        assert sorted_table(got).equals(sorted_table(without))
        assert "hybrid" in got.column("query").to_pylist()

    def test_too_much_appended_rejected(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))
        # triple the data (appended ratio ~0.75 > 0.3 default)
        raw = df.collect()
        for i in range(9):
            pq.write_table(raw, os.path.join(sample_parquet, f"big-{i}.parquet"))
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        plan = df2.filter(df2["clicks"] >= 500).select("clicks", "query").explain()
        assert "Hyperspace" not in plan


class TestMaintenanceGuard:
    def test_create_index_not_rewritten_by_own_index(
        self, session, hs, sample_parquet
    ):
        """Index maintenance must run with the rewrite rule disabled
        (ApplyHyperspace.withHyperspaceRuleDisabled)."""
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, CoveringIndexConfig("idx1", ["clicks"], ["query"]))
        session.enable_hyperspace()
        # creating a second index over the same df must scan the SOURCE
        hs.create_index(df, CoveringIndexConfig("idx2", ["clicks"], ["query"]))
        e2 = session.index_manager.get_index_log_entry("idx2")
        src = e2.relation.root_paths
        assert any(sample_parquet in p for p in src)
