"""Multi-host bootstrap + collective-witness dryrun (docs/MULTIHOST.md).

Runs ``scripts/dryrun_multihost.py`` — 2 REAL processes x 4 CPU devices
joined via ``initialize_distributed`` (gloo collectives) — asserting the
flat shard-axis ``all_to_all``/``psum``, the hierarchical (dcn, ici)
two-stage reduction, the process-local twostage bucket exchange AND a
2-process CREATE end to end (coordinator-gated metadata plane: one log
entry pair, identical global content on both processes). The run is
armed with ``HS_COLLECTIVE_WITNESS`` so each process records its ordered
collective sequence, and the test then merges the per-process artifacts
and requires ZERO cross-process divergence and zero unregistered
witnessed sites — the HS804 loop ``scripts/bench_smoke.sh`` gates on.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(REPO, "hyperspace_tpu")


def test_two_process_dryrun(tmp_path):
    script = os.path.join(REPO, "scripts", "dryrun_multihost.py")
    prefix = str(tmp_path / "cw")
    env = dict(os.environ, HS_COLLECTIVE_WITNESS=prefix)
    # the workers manage their own platform/device config; drop the test
    # session's forced XLA flags so they don't fight the workers'
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, script],
        capture_output=True,
        text=True,
        timeout=280,
        env=env,
        cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert out.stdout.count("DRYRUN-OK") == 2, out.stdout + out.stderr

    # merge the per-process artifacts and cross-check: zero divergence,
    # zero unregistered witnessed sites, coordinator gating honored
    from hyperspace_tpu.analysis import spmd
    from hyperspace_tpu.analysis.core import Project

    docs = spmd.load_collective_witness(prefix)
    assert [d["process"] for d in docs] == [0, 1], docs
    project = Project(PKG_DIR)
    findings, _warnings = spmd.collective_cross_check(
        [project], docs, "cw"
    )
    assert findings == [], "\n".join(f.render() for f in findings)
    # the CREATE must have driven the coordinator-gated metadata path
    p0_sites = {r["site"] for r in docs[0]["sequence"]}
    p1_sites = {r["site"] for r in docs[1]["sequence"]}
    assert "hyperspace_tpu.actions.base._publish_log" in p0_sites
    assert "hyperspace_tpu.actions.base._publish_log" not in p1_sites
