"""Multi-host bootstrap dryrun (docs/MULTIHOST.md).

Runs ``scripts/dryrun_multihost.py`` — 2 REAL processes x 4 CPU devices
joined via ``initialize_distributed`` (gloo collectives) — asserting the
flat shard-axis ``all_to_all``/``psum`` and the hierarchical (dcn, ici)
two-stage reduction both execute across the process boundary. This is
the CPU stand-in for the reference's delegated-to-Spark multi-node
scaling (SURVEY §2.11 driver/executor row).
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_dryrun():
    script = os.path.join(REPO, "scripts", "dryrun_multihost.py")
    env = dict(os.environ)
    # the workers manage their own platform/device config; drop the test
    # session's forced XLA flags so they don't fight the workers'
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, script],
        capture_output=True,
        text=True,
        timeout=280,
        env=env,
        cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert out.stdout.count("DRYRUN-OK") == 2, out.stdout + out.stderr
