"""Aggregate / Sort / Limit engine tests — differential vs pyarrow compute.

The reference delegates these to Spark; for us they are engine nodes
(VERDICT round-1 item 6). Differential style mirrors the reference's
``QueryTest.checkAnswer`` pattern: same answer as an independent engine.
"""

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import functions as F
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig


@pytest.fixture
def agg_data(tmp_path):
    rng = np.random.default_rng(5)
    n = 500
    t = pa.table(
        {
            "g": pa.array([f"k{int(x)}" for x in rng.integers(0, 7, n)]),
            "h": pa.array(rng.integers(0, 3, n), type=pa.int64()),
            "x": pa.array(rng.integers(-50, 50, n), type=pa.int64()),
            "y": pa.array(rng.normal(0, 10, n)),
            "s": pa.array(
                [["apple", "pear", "fig", None][int(x)] for x in rng.integers(0, 4, n)]
            ),
            "z": pa.array(
                [None if i % 11 == 0 else float(i % 13) for i in range(n)]
            ),
        }
    )
    d = tmp_path / "agg"
    d.mkdir()
    for i in range(2):
        pq.write_table(t.slice(i * 250, 250), d / f"p{i}.parquet")
    return str(d), t


def arrow_groupby(t, keys, aggs):
    """pyarrow reference implementation -> sorted table."""
    gb = t.group_by(keys)
    out = gb.aggregate(aggs)
    return out.sort_by([(k, "ascending") for k in keys])


def sorted_by(t, keys):
    return t.sort_by([(k, "ascending") for k in keys])


class TestAggregates:
    def test_grouped_sum_count_min_max_avg(self, session, agg_data):
        d, t = agg_data
        df = session.read.parquet(d)
        got = (
            df.group_by("g")
            .agg(
                F.sum("x").alias("sx"),
                F.count().alias("n"),
                F.count("z").alias("nz"),
                F.min("x").alias("mnx"),
                F.max("y").alias("mxy"),
                F.avg("x").alias("ax"),
            )
            .collect()
            .sort_by([("g", "ascending")])
        )
        ref = arrow_groupby(
            t,
            ["g"],
            [
                ("x", "sum"),
                ("g", "count"),
                ("z", "count"),
                ("x", "min"),
                ("y", "max"),
                ("x", "mean"),
            ],
        )
        assert got.column("sx").to_pylist() == ref.column("x_sum").to_pylist()
        assert got.column("n").to_pylist() == ref.column("g_count").to_pylist()
        assert got.column("nz").to_pylist() == ref.column("z_count").to_pylist()
        assert got.column("mnx").to_pylist() == ref.column("x_min").to_pylist()
        assert got.column("mxy").to_pylist() == pytest.approx(
            ref.column("y_max").to_pylist()
        )
        assert got.column("ax").to_pylist() == pytest.approx(
            ref.column("x_mean").to_pylist()
        )

    def test_multi_key_group(self, session, agg_data):
        d, t = agg_data
        df = session.read.parquet(d)
        got = (
            df.group_by("g", "h")
            .agg(F.sum("x").alias("sx"))
            .collect()
            .sort_by([("g", "ascending"), ("h", "ascending")])
        )
        ref = arrow_groupby(t, ["g", "h"], [("x", "sum")]).sort_by(
            [("g", "ascending"), ("h", "ascending")]
        )
        assert got.column("g").to_pylist() == ref.column("g").to_pylist()
        assert got.column("h").to_pylist() == ref.column("h").to_pylist()
        assert got.column("sx").to_pylist() == ref.column("x_sum").to_pylist()

    def test_global_aggregate(self, session, agg_data):
        d, t = agg_data
        df = session.read.parquet(d)
        got = df.agg(
            F.count().alias("n"), F.sum("x").alias("sx"), F.avg("y").alias("ay")
        ).collect()
        assert got.num_rows == 1
        assert got.column("n")[0].as_py() == t.num_rows
        assert got.column("sx")[0].as_py() == pc.sum(t.column("x")).as_py()
        assert got.column("ay")[0].as_py() == pytest.approx(
            pc.mean(t.column("y")).as_py()
        )

    def test_null_group_and_null_aggs(self, session, agg_data):
        d, t = agg_data
        df = session.read.parquet(d)
        # group by a column containing nulls: nulls form one group (SQL)
        got = (
            df.group_by("s")
            .agg(F.count().alias("n"), F.sum("x").alias("sx"))
            .collect()
        )
        got_by_key = {
            r["s"]: (r["n"], r["sx"]) for r in got.to_pylist()
        }
        ref = t.group_by("s").aggregate([([], "count_all"), ("x", "sum")])
        ref_by_key = {
            r["s"]: (r["count_all"], r["x_sum"]) for r in ref.to_pylist()
        }
        assert got_by_key == ref_by_key
        assert None in got_by_key  # the null group exists

    def test_string_min_max(self, session, agg_data):
        d, t = agg_data
        df = session.read.parquet(d)
        got = (
            df.group_by("h")
            .agg(F.min("s").alias("mn"), F.max("s").alias("mx"))
            .collect()
            .sort_by([("h", "ascending")])
        )
        ref = arrow_groupby(t, ["h"], [("s", "min"), ("s", "max")])
        assert got.column("mn").to_pylist() == ref.column("s_min").to_pylist()
        assert got.column("mx").to_pylist() == ref.column("s_max").to_pylist()

    def test_all_null_group_sum_is_null(self, session, tmp_path):
        t = pa.table(
            {
                "g": ["a", "a", "b"],
                "v": pa.array([None, None, 1.5], type=pa.float64()),
            }
        )
        d = tmp_path / "n"
        d.mkdir()
        pq.write_table(t, d / "p.parquet")
        df = session.read.parquet(str(d))
        got = (
            df.group_by("g")
            .agg(F.sum("v").alias("sv"), F.min("v").alias("mv"))
            .collect()
            .sort_by([("g", "ascending")])
        )
        assert got.column("sv").to_pylist() == [None, 1.5]
        assert got.column("mv").to_pylist() == [None, 1.5]

    def test_empty_input_global_agg(self, session, tmp_path):
        t = pa.table({"v": pa.array([], type=pa.int64())})
        d = tmp_path / "e"
        d.mkdir()
        pq.write_table(t, d / "p.parquet")
        df = session.read.parquet(str(d))
        got = df.agg(F.count().alias("n"), F.sum("v").alias("sv")).collect()
        assert got.column("n").to_pylist() == [0]
        assert got.column("sv").to_pylist() == [None]

    def test_agg_over_filter(self, session, agg_data):
        d, t = agg_data
        df = session.read.parquet(d)
        got = (
            df.filter(df["x"] > 0)
            .group_by("g")
            .agg(F.sum("x").alias("sx"))
            .collect()
            .sort_by([("g", "ascending")])
        )
        ft = t.filter(pc.greater(t.column("x"), 0))
        ref = arrow_groupby(ft, ["g"], [("x", "sum")])
        assert got.column("sx").to_pylist() == ref.column("x_sum").to_pylist()


class TestSortLimit:
    def test_sort_single_key(self, session, agg_data):
        d, t = agg_data
        df = session.read.parquet(d)
        got = df.sort("x").collect()
        ref = t.sort_by([("x", "ascending")])
        assert got.column("x").to_pylist() == ref.column("x").to_pylist()

    def test_sort_descending_and_multi_key(self, session, agg_data):
        d, t = agg_data
        df = session.read.parquet(d)
        got = df.sort("g", ("x", False)).collect()
        ref = t.sort_by([("g", "ascending"), ("x", "descending")])
        assert got.column("g").to_pylist() == ref.column("g").to_pylist()
        assert got.column("x").to_pylist() == ref.column("x").to_pylist()

    def test_sort_string_and_float_with_nulls(self, session, agg_data):
        d, t = agg_data
        df = session.read.parquet(d)
        got = df.sort("s", "z").collect()
        ref = t.sort_by([("s", "ascending"), ("z", "ascending")])
        assert got.column("s").to_pylist() == ref.column("s").to_pylist()
        assert got.column("z").to_pylist() == ref.column("z").to_pylist()

    def test_sort_floats_negative(self, session, tmp_path):
        vals = [3.5, -1.25, 0.0, -0.0, float("inf"), -float("inf"), 2.0, -7.5]
        t = pa.table({"v": pa.array(vals, type=pa.float64())})
        d = tmp_path / "f"
        d.mkdir()
        pq.write_table(t, d / "p.parquet")
        df = session.read.parquet(str(d))
        got = df.sort("v").collect().column("v").to_pylist()
        assert got == sorted(vals)
        got_desc = df.sort(("v", False)).collect().column("v").to_pylist()
        assert got_desc == sorted(vals, reverse=True)

    def test_limit(self, session, agg_data):
        d, t = agg_data
        df = session.read.parquet(d)
        got = df.sort("x").limit(7).collect()
        assert got.num_rows == 7
        ref = t.sort_by([("x", "ascending")]).slice(0, 7)
        assert got.column("x").to_pylist() == ref.column("x").to_pylist()
        assert df.limit(10**9).collect().num_rows == t.num_rows

    def test_index_served_filter_then_aggregate(self, session, agg_data):
        """Bench config 2 shape: range filter + aggregate over an index."""
        d, t = agg_data
        hs = Hyperspace(session)
        df = session.read.parquet(d)
        hs.create_index(df, CoveringIndexConfig("x_idx", ["x"], ["g", "y"]))
        q = lambda f: (
            f.filter(f["x"] > 10)
            .group_by("g")
            .agg(F.count().alias("n"), F.avg("y").alias("ay"))
        )
        session.disable_hyperspace()
        base = q(df).collect().sort_by([("g", "ascending")])
        session.enable_hyperspace()
        plan = q(df).explain()
        assert "Hyperspace(Type: CI, Name: x_idx" in plan, plan
        got = q(df).collect().sort_by([("g", "ascending")])
        assert got.column("g").to_pylist() == base.column("g").to_pylist()
        assert got.column("n").to_pylist() == base.column("n").to_pylist()
        assert got.column("ay").to_pylist() == pytest.approx(
            base.column("ay").to_pylist()
        )

    def test_nan_min_max_spark_semantics(self, session, tmp_path):
        """NaN > +inf (Spark float ordering, consistent with sort)."""
        t = pa.table(
            {
                "g": ["a", "a", "b", "b", "c"],
                "v": pa.array(
                    [1.0, float("nan"), float("nan"), float("nan"), 2.0],
                    type=pa.float64(),
                ),
            }
        )
        d = tmp_path / "nan"
        d.mkdir()
        pq.write_table(t, d / "p.parquet")
        df = session.read.parquet(str(d))
        got = (
            df.group_by("g")
            .agg(F.min("v").alias("mn"), F.max("v").alias("mx"))
            .collect()
            .sort_by([("g", "ascending")])
        )
        mn = got.column("mn").to_pylist()
        mx = got.column("mx").to_pylist()
        assert mn[0] == 1.0 and np.isnan(mx[0])  # NaN wins max
        assert np.isnan(mn[1]) and np.isnan(mx[1])  # all-NaN group
        assert mn[2] == 2.0 and mx[2] == 2.0

    def test_plan_time_type_validation(self, session, agg_data):
        from hyperspace_tpu.exceptions import HyperspaceException

        d, t = agg_data
        df = session.read.parquet(d)
        with pytest.raises(HyperspaceException, match="avg"):
            df.group_by("g").agg(F.avg("s")).schema()
        with pytest.raises(HyperspaceException, match="sum"):
            df.group_by("g").agg(F.sum("s")).schema()


def test_segment_ops_host_device_equivalent():
    """The small-input host reductions and the device segment kernels must
    agree (incl. int64 exactness, null handling and NaN min/max rules)."""
    import numpy as np

    from hyperspace_tpu.ops import aggregate as A

    rng = np.random.default_rng(1)
    n, g = 5000, 37
    gid = rng.integers(0, g, n)
    ints = rng.integers(-(2**40), 2**40, n, dtype=np.int64)
    flts = rng.normal(size=n)
    flts[rng.random(n) < 0.05] = np.nan
    valid = rng.random(n) > 0.1

    def both(fn, *args):
        host = fn(*args)
        old = A._HOST_AGG_MAX_ROWS
        try:
            A._HOST_AGG_MAX_ROWS = 0
            dev = fn(*args)
        finally:
            A._HOST_AGG_MAX_ROWS = old
        return host, dev

    (hs, hc), (ds, dc) = both(A.segment_sum_count, gid, ints, valid, g)
    assert np.array_equal(hs, ds) and np.array_equal(hc, dc)
    for mode in ("min", "max"):
        h, d = both(A.segment_minmax, gid, ints, valid, g, mode)
        assert np.array_equal(h, d), mode
        h, d = both(A.segment_minmax, gid, flts, valid, g, mode)
        assert np.array_equal(h, d, equal_nan=True), mode
    h, d = both(A.segment_count, gid, valid, n, g)
    assert np.array_equal(h, d)


def test_uint8_sum_does_not_wrap():
    import numpy as np

    from hyperspace_tpu.ops import aggregate as A

    gid = np.zeros(2, dtype=np.int64)
    vals = np.array([200, 200], dtype=np.uint8)
    s, c = A.segment_sum_count(gid, vals, None, 1)
    assert int(s[0]) == 400 and int(c[0]) == 2
