"""Exchange-strategy plane (``hyperspace.build.exchange.strategy``) —
the differential matrix.

The contract: every strategy (``host`` pure-RAM reorder, ``compact``
host-packed exact-extent all_to_all, ``twostage`` DCN/ICI decomposition
with per-peer round caps) produces BIT-IDENTICAL output to the ``flat``
padded all_to_all baseline — same bucket ids, same payload rows in the
same order, same ``with_shard_offsets`` extents — across mesh sizes,
payload types (ints, strings via dictionary codes, validity masks,
floats with NaNs), skews (uniform and one hot bucket) and the
empty-shard edge (a peer that owns zero rows). Session-level legs check
the parquet bytes of whole builds, including streaming waves.
"""

import hashlib
import logging
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import jax

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.parallel import shuffle as sh


def _mesh(n_devices):
    return jax.sharding.Mesh(
        np.array(jax.devices()[:n_devices]), (sh.SHARD_AXIS,)
    )


def _payload_matrix(rng, n):
    """One array per payload kind the build decomposes batches into:
    int64 key reps/values, float64 with NaNs, int32 dictionary codes
    (strings), bool validity masks."""
    f = rng.normal(size=n)
    f[rng.integers(0, 2, n).astype(bool)] = np.nan
    return [
        rng.integers(-(2**60), 2**60, n).astype(np.int64),
        f,
        rng.integers(0, 3, n).astype(np.int32),
        rng.integers(0, 2, n).astype(bool),
    ]


def _keys(rng, n, skew):
    if skew == "hot":  # every row hashes into ONE bucket
        return np.full((1, n), 7, dtype=np.int64)
    return rng.integers(0, 97, (2, n)).astype(np.int64)


def _strategies_for(D):
    out = [sh.STRATEGY_HOST, sh.STRATEGY_COMPACT]
    if D > 1:
        out.append(sh.STRATEGY_TWOSTAGE)
    return out


class TestStrategyDifferential:
    @pytest.mark.parametrize("D", [1, 2, 8])
    @pytest.mark.parametrize("skew", ["uniform", "hot"])
    def test_bit_identical_to_flat(self, D, skew):
        mesh = _mesh(D)
        rng = np.random.default_rng(D * 31 + len(skew))
        n, nb = 3001, 16
        keys = _keys(rng, n, skew)
        payloads = _payload_matrix(rng, n)
        ref = sh.bucket_shuffle(
            mesh, keys, payloads, nb, with_shard_offsets=True,
            strategy=sh.STRATEGY_FLAT,
        )
        for strat in _strategies_for(D):
            got = sh.bucket_shuffle(
                mesh, keys, payloads, nb, with_shard_offsets=True,
                strategy=strat, twostage_hosts=2,
            )
            np.testing.assert_array_equal(got[0], ref[0], err_msg=strat)
            np.testing.assert_array_equal(got[2], ref[2], err_msg=strat)
            assert len(got[1]) == len(ref[1])
            for a, b in zip(got[1], ref[1]):
                assert a.dtype == b.dtype, strat
                np.testing.assert_array_equal(a, b, err_msg=strat)
            assert sh.last_shuffle_stats["strategy"] == strat

    def test_empty_peer_extents(self):
        """num_buckets < D: some shards own no buckets and must report
        empty ``with_shard_offsets`` extents in every strategy."""
        mesh = _mesh(8)
        rng = np.random.default_rng(3)
        n, nb = 999, 3  # owners only 0..2 of 8 shards
        keys = rng.integers(0, 50, (1, n)).astype(np.int64)
        payloads = [np.arange(n, dtype=np.int64)]
        ref = sh.bucket_shuffle(
            mesh, keys, payloads, nb, with_shard_offsets=True,
            strategy=sh.STRATEGY_FLAT,
        )
        assert (np.diff(ref[2])[nb:] == 0).all()
        for strat in _strategies_for(8):
            got = sh.bucket_shuffle(
                mesh, keys, payloads, nb, with_shard_offsets=True,
                strategy=strat, twostage_hosts=4,
            )
            np.testing.assert_array_equal(got[0], ref[0], err_msg=strat)
            np.testing.assert_array_equal(got[2], ref[2], err_msg=strat)
            np.testing.assert_array_equal(got[1][0], ref[1][0], err_msg=strat)

    @pytest.mark.parametrize("hosts", [2, 4, 8])
    def test_twostage_host_factorizations(self, hosts):
        """Every (H, L) carve of the 8-device mesh lands the same rows."""
        mesh = _mesh(8)
        rng = np.random.default_rng(hosts)
        n, nb = 2048, 16
        keys = rng.integers(0, 200, (1, n)).astype(np.int64)
        payloads = [keys[0], rng.normal(size=n)]
        ref = sh.bucket_shuffle(
            mesh, keys, payloads, nb, with_shard_offsets=True,
            strategy=sh.STRATEGY_FLAT,
        )
        got = sh.bucket_shuffle(
            mesh, keys, payloads, nb, with_shard_offsets=True,
            strategy=sh.STRATEGY_TWOSTAGE, twostage_hosts=hosts,
        )
        np.testing.assert_array_equal(got[0], ref[0])
        np.testing.assert_array_equal(got[2], ref[2])
        for a, b in zip(got[1], ref[1]):
            np.testing.assert_array_equal(a, b)
        assert sh.last_shuffle_stats["hosts"] == float(hosts)

    def test_canonical_order_is_flat_order(self):
        """The host-side permutation equals the naive (owner, bucket,
        row) lexsort — the invariant every non-flat strategy rides."""
        rng = np.random.default_rng(11)
        n, nb, D = 5000, 13, 8
        ids = rng.integers(0, nb, n).astype(np.int32)
        perm, offs = sh.canonical_order(ids, nb, D)
        ref = np.lexsort((np.arange(n), ids, ids % D))
        np.testing.assert_array_equal(perm, ref)
        np.testing.assert_array_equal(
            np.diff(offs), np.bincount(ids % D, minlength=D)
        )

    def test_resolve(self):
        mesh = _mesh(8)
        # CPU mesh: auto must pick the host-side exchange
        assert sh.resolve_strategy("auto", mesh, 10**6) == sh.STRATEGY_HOST
        assert sh.resolve_strategy("flat", mesh, 10) == sh.STRATEGY_FLAT
        assert (
            sh.resolve_strategy("TwoStage", mesh, 10)
            == sh.STRATEGY_TWOSTAGE
        )
        with pytest.raises(ValueError, match="unknown exchange strategy"):
            sh.resolve_strategy("bogus", mesh, 10)


# ---------------------------------------------------------------------------
# Session-level: whole builds, parquet bytes
# ---------------------------------------------------------------------------


@pytest.fixture
def mesh8(session_factory):
    return session_factory(8)


@pytest.fixture
def mixed_parquet(tmp_path):
    rng = np.random.default_rng(17)
    d = tmp_path / "mixed"
    d.mkdir()
    for i in range(4):
        n = 2500
        vals = rng.normal(size=n)
        t = pa.table(
            {
                "k": pa.array(rng.integers(0, 40, n), type=pa.int64()),
                "s": pa.array(
                    [["aa", "bb", "cc"][v] for v in rng.integers(0, 3, n)]
                ),
                "v": pa.array(
                    [None if j % 13 == 0 else vals[j] for j in range(n)],
                    type=pa.float64(),
                ),
            }
        )
        pq.write_table(t, d / f"part-{i}.parquet")
    return str(d)


def _sha(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _build(session, src, name, strategy, budget=0, hosts=0):
    session.conf.set(C.BUILD_EXCHANGE_STRATEGY, strategy)
    session.conf.set(C.BUILD_EXCHANGE_TWOSTAGE_HOSTS, hosts)
    session.conf.set(C.INDEX_BUILD_MEMORY_BUDGET, budget)
    hs = Hyperspace(session)
    df = session.read.parquet(src)
    hs.create_index(df, CoveringIndexConfig(name, ["k"], ["s", "v"]))
    entry = session.index_manager.get_index_log_entry(name)
    return sorted(entry.content.files)


def _assert_identical_files(files_a, files_b, tag):
    assert [os.path.basename(f) for f in files_a] == [
        os.path.basename(f) for f in files_b
    ], tag
    for fa, fb in zip(files_a, files_b):
        assert _sha(fa) == _sha(fb), f"{tag}: parquet bytes differ: {fa}"


class TestBuildDifferential:
    def test_in_memory_builds_bit_identical(self, mesh8, mixed_parquet):
        ref = _build(mesh8, mixed_parquet, "exflat", "flat")
        from hyperspace_tpu.indexes.covering_build import last_build_telemetry

        for strat in ("auto", "host", "compact", "twostage"):
            files = _build(
                mesh8, mixed_parquet, f"ex{strat}", strat, hosts=2
            )
            _assert_identical_files(files, ref, strat)
            expect = "host" if strat == "auto" else strat
            assert last_build_telemetry["shuffle_strategy"] == expect

    def test_streaming_waves_bit_identical(self, mesh8, mixed_parquet):
        from hyperspace_tpu.indexes.covering_build import (
            per_file_materialized_bytes,
        )

        first = sorted(os.listdir(mixed_parquet))[0]
        per_file = per_file_materialized_bytes(
            [os.path.join(mixed_parquet, first)], "parquet"
        )[0]
        budget = int(per_file * 1.5)  # several waves
        ref = _build(mesh8, mixed_parquet, "stflat", "flat", budget=budget)
        from hyperspace_tpu.indexes.covering_build import last_build_telemetry

        for strat in ("host", "compact", "twostage"):
            files = _build(
                mesh8, mixed_parquet, f"st{strat}", strat,
                budget=budget, hosts=2,
            )
            _assert_identical_files(files, ref, strat)
            assert last_build_telemetry["shuffle_waves"] > 1
            assert "shuffle_skew_ratio_max" in last_build_telemetry
            assert "shuffle_skew_ratio_mean" in last_build_telemetry

    def test_stage_seconds_and_strategy_in_telemetry(self, mesh8, mixed_parquet):
        from hyperspace_tpu.indexes.covering_build import last_build_telemetry

        _build(mesh8, mixed_parquet, "tele", "auto")
        t = last_build_telemetry
        assert t["shuffle_strategy"] == "host"
        for key in ("shuffle_pack_s", "shuffle_exchange_s", "shuffle_unpack_s"):
            assert key in t, t
        assert t["shuffle_devices"] == 8.0


class TestSkewWarnRateLimit:
    def test_streaming_build_warns_once(self, mesh8, tmp_path, caplog):
        """A skewed streaming build runs one exchange per wave; the skew
        warning must fire ONCE per build while telemetry records every
        wave as a max/mean pair."""
        d = tmp_path / "skew"
        d.mkdir()
        # per wave (one file), every shard sends all its rows to ONE
        # peer: n/8 per (shard, peer) slot must clear the warn floor
        n = 40000
        t = pa.table(
            {
                "k": pa.array(np.full(n, 7), type=pa.int64()),
                "s": pa.array(["x"] * n),
                "v": pa.array(np.ones(n)),
            }
        )
        for i in range(4):
            pq.write_table(t, d / f"p{i}.parquet")
        from hyperspace_tpu.indexes.covering_build import (
            last_build_telemetry,
            per_file_materialized_bytes,
        )

        per_file = per_file_materialized_bytes(
            [str(d / "p0.parquet")], "parquet"
        )[0]
        with caplog.at_level(logging.WARNING, "hyperspace_tpu.shuffle"):
            _build(
                mesh8, str(d), "skew1x", "auto", budget=int(per_file * 1.5)
            )
        warns = [r for r in caplog.records if "shuffle skew" in r.message]
        assert len(warns) == 1, warns
        tele = last_build_telemetry
        assert tele["shuffle_waves"] > 1
        assert tele["shuffle_skew_ratio_max"] >= C.BUILD_SHUFFLE_SKEW_WARN_RATIO
        assert tele["shuffle_skew_ratio_mean"] > 1.0
        # a second build warns again (fresh latch per data op)
        caplog.clear()
        with caplog.at_level(logging.WARNING, "hyperspace_tpu.shuffle"):
            _build(
                mesh8, str(d), "skew2x", "auto", budget=int(per_file * 1.5)
            )
        warns = [r for r in caplog.records if "shuffle skew" in r.message]
        assert len(warns) == 1, warns
