"""Range serve plane: zone-map pruning superset-safety property suite.

The contract under test (docs/range-serve.md, indexes/zonemaps.py):
pruned-scan ≡ full-scan+mask for EVERY predicate and dtype — pruning may
only drop files/row groups no matching row can live in. The suite runs
the three-way differential (rangeprune on ≡ rangeprune off ≡ unindexed)
across the dtype matrix (ints, floats with NaN, strings, dates, tz
timestamps, nullable columns), checks lifecycle operations
(refresh/optimize) keep zone maps consistent, and exercises stale
sidecar eviction, the hybrid-scan fallback, and the z-address range
decomposition's covering property.
"""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes import zonemaps
from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig
from hyperspace_tpu.plan import expressions as E


@pytest.fixture
def s1(session_factory):
    """Mesh-1 session: pruning is a host read-side feature with no mesh
    axis; one size keeps the dtype matrix fast."""
    return session_factory(1)


def _write_files(tmp_path, name, table, n_files=4):
    d = tmp_path / name
    d.mkdir()
    n = table.num_rows
    for i in range(n_files):
        lo, hi = i * n // n_files, (i + 1) * n // n_files
        pq.write_table(table.slice(lo, hi - lo), str(d / f"part{i}.parquet"))
    return str(d)


def _three_way(session, df, cond_fn, select_cols):
    """collect() with rangeprune on vs off vs unindexed; all must be
    bit-identical (same rows, same order)."""
    q = lambda: df.filter(cond_fn(df)).select(*select_cols).collect()
    session.enable_hyperspace()
    session.conf.set(C.SERVE_RANGEPRUNE_ENABLED, True)
    zonemaps.invalidate_local_cache()
    on = q()
    session.conf.set(C.SERVE_RANGEPRUNE_ENABLED, False)
    off = q()
    session.conf.unset(C.SERVE_RANGEPRUNE_ENABLED)
    session.disable_hyperspace()
    raw = q()
    assert on.equals(off), "rangeprune on/off results differ"
    assert on.num_rows == raw.num_rows, (on.num_rows, raw.num_rows)
    return on


class TestIntervalExtraction:
    SCHEMA = {
        "i": pa.int64(),
        "f": pa.float64(),
        "s": pa.string(),
        "d": pa.date32(),
    }

    def test_range_conjuncts_intersect(self):
        cond = (E.Col("i") >= 3) & (E.Col("i") < 10) & (E.Col("i") > 4)
        iv = zonemaps.predicate_intervals(cond, self.SCHEMA)["i"]
        assert (iv.lo, iv.lo_strict, iv.hi, iv.hi_strict) == (4, True, 10, True)

    def test_eq_and_contradiction(self):
        cond = (E.Col("i") == 5) & (E.Col("i") > 7)
        assert zonemaps.predicate_intervals(cond, self.SCHEMA)["i"].empty

    def test_in_hull_and_ne_abstains(self):
        cond = E.Col("i").isin(3, 9, 5) & (E.Col("f") != 1.0)
        out = zonemaps.predicate_intervals(cond, self.SCHEMA)
        assert (out["i"].lo, out["i"].hi) == (3, 9)
        assert "f" not in out  # != never contributes

    def test_temporal_lowering_matches_engine(self):
        # sub-day instant on a date column: equality can never hold
        cond = E.Col("d") == "2020-01-01T12:00:00"
        assert zonemaps.predicate_intervals(cond, self.SCHEMA)["d"].empty
        # range ops snap between ticks, op-aware
        cond = E.Col("d") > "2020-01-01T12:00:00"
        iv = zonemaps.predicate_intervals(cond, self.SCHEMA)["d"]
        assert iv.lo is not None and not iv.empty

    def test_string_columns_str_cast(self):
        cond = (E.Col("s") >= "b") & (E.Col("s") < "m")
        iv = zonemaps.predicate_intervals(cond, self.SCHEMA)["s"]
        assert (iv.lo, iv.hi) == ("b", "m")

    def test_case_insensitive_and_or_abstains(self):
        cond = (E.Col("I") >= 1) & ((E.Col("f") > 0) | (E.Col("i") < 0))
        out = zonemaps.predicate_intervals(cond, self.SCHEMA)
        assert out["i"].lo == 1 and "f" not in out


class TestZBoxRanges:
    """The decomposition's covering property: the union of emitted
    ranges contains EVERY z-address inside the box (over-covering is
    allowed, under-covering never)."""

    @staticmethod
    def _z(x, y, bits):
        z = 0
        for t in range(2 * bits):
            col = (x, y)[t % 2]
            bit = (col >> (bits - 1 - t // 2)) & 1
            z = (z << 1) | bit
        return z

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_covers_box(self, seed):
        from hyperspace_tpu.ops.zorder import z_box_ranges

        bits = 4
        rng = np.random.default_rng(seed)
        lo = rng.integers(0, 1 << bits, 2)
        hi = [int(rng.integers(l, 1 << bits)) for l in lo]
        ranges = z_box_ranges(list(map(int, lo)), hi, bits, max_ranges=8)
        for x in range(int(lo[0]), hi[0] + 1):
            for y in range(int(lo[1]), hi[1] + 1):
                z = self._z(x, y, bits)
                assert any(a <= z <= b for a, b in ranges), (x, y, z)

    def test_full_box_is_one_range(self):
        from hyperspace_tpu.ops.zorder import z_box_ranges

        ranges = z_box_ranges([0, 0], [15, 15], 4)
        assert ranges == [(0, 255)]

    def test_budget_caps_range_count(self):
        from hyperspace_tpu.ops.zorder import z_box_ranges

        ranges = z_box_ranges([1, 3], [14, 11], 8, max_ranges=4)
        assert len(ranges) <= 4 * 4 + 1


def _dtype_tables(rng, n=8000):
    base = np.datetime64("2019-01-01")
    days = np.sort(rng.integers(0, 900, n))
    yield "ints", {
        "c": pa.array(np.sort(rng.integers(-1000, 1000, n)), type=pa.int64()),
        "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
    }, lambda df: (df["c"] >= -100) & (df["c"] < 250)
    f = rng.normal(0, 100, n)
    f[::31] = np.nan
    yield "floats_nan", {
        "c": pa.array(f),
        "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
    }, lambda df: (df["c"] > -50.0) & (df["c"] <= 50.0)
    yield "strings", {
        "c": pa.array([f"k{int(v):06d}" for v in rng.integers(0, 5000, n)]),
        "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
    }, lambda df: (df["c"] >= "k001000") & (df["c"] < "k002000")
    yield "dates", {
        "c": pa.array((base + days).astype("datetime64[D]")),
        "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
    }, lambda df: (
        (df["c"] >= np.datetime64("2019-06-01"))
        & (df["c"] <= np.datetime64("2019-09-01"))
    )
    yield "ts_tz", {
        "c": pa.array(
            (base + days).astype("datetime64[us]"),
            type=pa.timestamp("us", tz="UTC"),
        ),
        "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
    }, lambda df: (df["c"] >= "2019-06-01") & (df["c"] < "2019-09-01")
    yield "nullable_int", {
        "c": pa.array(
            [None if i % 11 == 0 else int(v) for i, v in enumerate(
                np.sort(rng.integers(0, 10_000, n))
            )],
            type=pa.int64(),
        ),
        "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
    }, lambda df: (df["c"] > 2000) & (df["c"] <= 4000)


class TestSupersetSafetyMatrix:
    """pruned ≡ unpruned across the dtype matrix, served by a z-order
    index (ANY indexed column may appear in the predicate, and the
    z-span decomposition path runs too)."""

    def test_dtype_matrix(self, s1, tmp_path):
        hs = Hyperspace(s1)
        rng = np.random.default_rng(7)
        for name, arrays, cond_fn in _dtype_tables(rng):
            d = _write_files(tmp_path, name, pa.table(arrays))
            df = s1.read.parquet(d)
            hs.create_index(
                df, ZOrderCoveringIndexConfig(f"z_{name}", ["c"], ["p"])
            )
            out = _three_way(s1, df, cond_fn, ["c", "p"])
            # sanity: the predicate actually selects a strict subset
            assert 0 < out.num_rows < pa.table(arrays).num_rows, name
            hs.delete_index(f"z_{name}")
            hs.vacuum_index(f"z_{name}")

    def test_eq_and_in_predicates(self, s1, tmp_path):
        hs = Hyperspace(s1)
        rng = np.random.default_rng(11)
        arrays = {
            "c": pa.array(
                np.sort(rng.integers(0, 500, 6000)), type=pa.int64()
            ),
            "p": pa.array(rng.integers(0, 10, 6000), type=pa.int64()),
        }
        d = _write_files(tmp_path, "eqin", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(df, ZOrderCoveringIndexConfig("z_eqin", ["c"], ["p"]))
        _three_way(s1, df, lambda df: df["c"] == 123, ["c", "p"])
        _three_way(
            s1, df, lambda df: df["c"].isin(5, 123, 499), ["c", "p"]
        )
        # contradiction: prunes everything, still equals the mask path
        out = _three_way(
            s1, df, lambda df: (df["c"] > 400) & (df["c"] < 100), ["c", "p"]
        )
        assert out.num_rows == 0

    def test_string_allnull_and_missing_stats(self, s1, tmp_path):
        """A file holding only NULL strings must prune under a string
        comparison (nulls never satisfy it) without tripping the
        object-array compares; results stay three-way identical."""
        hs = Hyperspace(s1)
        d = tmp_path / "strnull"
        d.mkdir()
        t1 = pa.table(
            {
                "c": pa.array([f"v{i:04d}" for i in range(2000)]),
                "p": pa.array(np.arange(2000), type=pa.int64()),
            }
        )
        t2 = pa.table(
            {
                "c": pa.array([None] * 500, type=pa.string()),
                "p": pa.array(np.arange(500), type=pa.int64()),
            }
        )
        pq.write_table(t1, str(d / "a.parquet"))
        pq.write_table(t2, str(d / "b.parquet"))
        df = s1.read.parquet(str(d))
        hs.create_index(df, ZOrderCoveringIndexConfig("z_sn", ["c"], ["p"]))
        out = _three_way(
            s1, df, lambda df: (df["c"] >= "v0100") & (df["c"] < "v0200"),
            ["c", "p"],
        )
        assert out.num_rows == 100

    def test_pruning_actually_prunes(self, s1, tmp_path):
        """On date-sorted files, a narrow range drops files AND row
        groups — and the telemetry says so."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(13)
        n = 8000
        arrays = {
            "c": pa.array(
                np.sort(rng.integers(0, 100_000, n)), type=pa.int64()
            ),
            "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        }
        d = _write_files(tmp_path, "prunes", pa.table(arrays))
        df = s1.read.parquet(d)
        # small target bytes → several z files, so FILE-level pruning has
        # something to drop even below one 64k row group
        s1.conf.set(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 16 * 1024)
        hs.create_index(df, ZOrderCoveringIndexConfig("z_pr", ["c"], ["p"]))
        s1.conf.unset(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION)
        s1.enable_hyperspace()
        zonemaps.invalidate_local_cache()
        df.filter((df["c"] >= 10_000) & (df["c"] < 12_000)).select(
            "c", "p"
        ).collect()
        st = zonemaps.last_prune_stats
        assert st["files_kept"] < st["files_total"] or (
            st["row_groups_kept"] < st["row_groups_total"]
        ), st
        assert st["zonemap_files_sidecar"] > 0  # capture fed the serve
        s1.disable_hyperspace()


class TestRowGroupNarrowing:
    def test_row_group_read_matches_full(self, tmp_path):
        from hyperspace_tpu.io import parquet as pio

        rng = np.random.default_rng(3)
        t = pa.table({"a": rng.integers(0, 100, 10_000)})
        p = str(tmp_path / "rg.parquet")
        pq.write_table(t, p, row_group_size=1000)
        full = pio.read_table_row_groups([p], [None], ["a"])
        assert full.equals(pq.read_table(p))
        sel = pio.read_table_row_groups([p], [(0, 3, 7)], ["a"])
        ref = pa.concat_tables(
            [pq.ParquetFile(p).read_row_groups([i], columns=["a"]) for i in (0, 3, 7)]
        )
        assert sel.equals(ref)
        empty = pio.read_table_row_groups([p], [()], ["a"])
        assert empty.num_rows == 0 and empty.column_names == ["a"]

    def test_multi_group_narrowing_end_to_end(self, s1, tmp_path):
        """>64k rows → multiple row groups per index file; a narrow
        range must keep a minority of groups with identical results."""
        hs = Hyperspace(s1)
        rng = np.random.default_rng(5)
        n = 200_000
        arrays = {
            "c": pa.array(np.sort(rng.integers(0, 10**6, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        }
        d = _write_files(tmp_path, "big", pa.table(arrays), n_files=2)
        df = s1.read.parquet(d)
        hs.create_index(df, ZOrderCoveringIndexConfig("z_big", ["c"], ["p"]))
        out = _three_way(
            s1,
            df,
            lambda df: (df["c"] >= 500_000) & (df["c"] < 520_000),
            ["c", "p"],
        )
        assert out.num_rows > 0
        st = zonemaps.last_prune_stats
        assert st["row_groups_total"] >= 3
        assert st["row_groups_kept"] < st["row_groups_total"], st


class TestLifecycleConsistency:
    def test_refresh_and_optimize_keep_maps_consistent(self, s1, tmp_path):
        from hyperspace_tpu.indexes.covering import CoveringIndexConfig

        hs = Hyperspace(s1)
        rng = np.random.default_rng(17)
        n = 6000
        arrays = {
            "k": pa.array(np.sort(rng.integers(0, 5000, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        }
        d = _write_files(tmp_path, "life", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(df, CoveringIndexConfig("ci", ["k"], ["p"]))
        cond = lambda df: (df["k"] >= 1000) & (df["k"] < 1500)
        _three_way(s1, df, cond, ["k", "p"])
        # append + incremental refresh: the new version dir gets its own
        # sidecar; old files keep theirs
        extra = pa.table(
            {
                "k": pa.array(
                    rng.integers(0, 5000, 500), type=pa.int64()
                ),
                "p": pa.array(rng.integers(0, 10, 500), type=pa.int64()),
            }
        )
        pq.write_table(extra, os.path.join(d, "part9.parquet"))
        s1.index_manager.clear_cache()
        hs.refresh_index("ci", C.REFRESH_MODE_INCREMENTAL)
        df2 = s1.read.parquet(d)
        _three_way(s1, df2, cond, ["k", "p"])
        # optimize compacts buckets into a new version dir + fresh sidecar
        hs.optimize_index("ci", mode=C.OPTIMIZE_MODE_FULL)
        _three_way(s1, df2, cond, ["k", "p"])
        entry = s1.index_manager.get_index_log_entry("ci")
        dirs = {os.path.dirname(f) for f in entry.content.files}
        for vd in dirs:
            assert os.path.exists(os.path.join(vd, zonemaps.SIDECAR_NAME))


class TestStaleEviction:
    def test_rewritten_file_ignores_stale_sidecar(self, tmp_path):
        rng = np.random.default_rng(19)
        p = str(tmp_path / "f.parquet")
        pq.write_table(
            pa.table({"a": rng.integers(0, 100, 1000)}), p, row_group_size=500
        )

        class _FakeIndex:
            kind = "CoveringIndex"
            indexed_columns = ["a"]

        assert zonemaps.capture_index_dir(str(tmp_path), _FakeIndex())
        side = zonemaps._sidecar_for_dir(str(tmp_path))
        assert zonemaps._file_stats_from_sidecar(p, side) is not None
        # rewrite the file: size/mtime change, the sidecar entry is stale
        pq.write_table(
            pa.table({"a": rng.integers(500, 600, 2000)}),
            p,
            row_group_size=500,
        )
        assert zonemaps._file_stats_from_sidecar(p, side) is None
        # assembly falls back to the (fresh) footer and stays correct
        zd = zonemaps.assemble_zone_data((p,), {"a": pa.int64()})
        assert zd.footer_files == 1 and zd.sidecar_files == 0
        cz = zd.cols["a"]
        assert cz.has.all() and float(cz.lo.min()) >= 500.0

    def test_serve_cache_zonemap_kind_evicts(self, tmp_path):
        from hyperspace_tpu.execution.serve_cache import ServeCache

        rng = np.random.default_rng(23)
        p = str(tmp_path / "g.parquet")
        pq.write_table(pa.table({"a": rng.integers(0, 100, 100)}), p)

        import dataclasses

        from hyperspace_tpu.plan.nodes import Relation

        rel = Relation(
            root_paths=(str(tmp_path),),
            files=(p,),
            fmt="parquet",
            schema_fields=(("a", pa.int64()),),
            index_info=("x", 1, "CI"),
        )
        cache = ServeCache(1 << 20)
        zonemaps.invalidate_local_cache()
        zd, hit = zonemaps.zone_data_for(rel, cache)
        assert not hit and len(cache) == 1
        zonemaps.invalidate_local_cache()
        _zd2, hit2 = zonemaps.zone_data_for(rel, cache)
        assert hit2
        assert cache.evict_kind("zonemap") == 1
        dataclasses.replace(rel)  # keep dataclasses import honest


class TestHybridFallback:
    def test_appended_files_read_in_full(self, s1, tmp_path):
        """Hybrid-scan filter: the index side prunes, the appended-files
        compensation side (no index_info) is never narrowed — and the
        union result matches the unindexed scan exactly."""
        from hyperspace_tpu.indexes.covering import CoveringIndexConfig

        hs = Hyperspace(s1)
        rng = np.random.default_rng(29)
        n = 4000
        arrays = {
            "k": pa.array(np.sort(rng.integers(0, 5000, n)), type=pa.int64()),
            "p": pa.array(rng.integers(0, 10, n), type=pa.int64()),
        }
        d = _write_files(tmp_path, "hyb", pa.table(arrays))
        df = s1.read.parquet(d)
        hs.create_index(df, CoveringIndexConfig("hci", ["k"], ["p"]))
        extra = pa.table(
            {
                "k": pa.array(rng.integers(0, 5000, 300), type=pa.int64()),
                "p": pa.array(rng.integers(0, 10, 300), type=pa.int64()),
            }
        )
        pq.write_table(extra, os.path.join(d, "appended.parquet"))
        s1.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        s1.index_manager.clear_cache()
        df2 = s1.read.parquet(d)
        out = _three_way(
            s1, df2, lambda df: (df["k"] >= 1000) & (df["k"] < 2000), ["k", "p"]
        )
        assert out.num_rows > 0
        s1.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, False)


class TestSidecarFormat:
    def test_sidecar_roundtrip_values(self, tmp_path):
        import datetime as dt

        for v in [
            None,
            True,
            -5,
            2.5,
            "abc",
            dt.date(2020, 1, 2),
            dt.datetime(2020, 1, 2, 3, 4, 5, 123456),
            dt.datetime(2020, 1, 2, tzinfo=dt.timezone.utc),
            dt.time(23, 59, 59),
            dt.timedelta(days=2, seconds=3, microseconds=4),
        ]:
            enc = zonemaps._enc_stat(v)
            json.dumps(enc)  # must be JSON-serializable
            assert zonemaps._dec_stat(enc) == v
        assert zonemaps._dec_stat(zonemaps._enc_stat(object())) is None
