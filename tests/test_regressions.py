"""Regression tests for advisor findings.

(a) the in-band NULL key rep must never make a real int64 key behave as
    null (joins dropping matches, aggregates mis-grouping);
(b) descending float sorts keep NaN after values (pyarrow semantics);
(c) sum/avg over booleans are rejected at plan time (Spark analysis-time
    behavior); min/max(bool) stays legal;
(d) limit does not execute/sort the full child.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import functions as F
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import NULL_KEY_REP

SENTINEL = int(NULL_KEY_REP)  # a perfectly legal int64 key value


def _write(tmp_path, name, table, n_files=1):
    d = tmp_path / name
    d.mkdir()
    rows = table.num_rows
    for i in range(n_files):
        lo = i * rows // n_files
        hi = (i + 1) * rows // n_files
        pq.write_table(table.slice(lo, hi - lo), d / f"p{i}.parquet")
    return str(d)


class TestNullSentinelCollision:
    def test_join_matches_real_key_equal_to_sentinel(self, session, tmp_path):
        left = pa.table(
            {
                "k": pa.array([SENTINEL, 1, None], type=pa.int64()),
                "lv": pa.array([10, 11, 12], type=pa.int64()),
            }
        )
        right = pa.table(
            {
                "j": pa.array([SENTINEL, None, 2], type=pa.int64()),
                "rv": pa.array([20, 21, 22], type=pa.int64()),
            }
        )
        dl = session.read.parquet(_write(tmp_path, "l", left))
        dr = session.read.parquet(_write(tmp_path, "r", right))
        out = dl.join(dr, on=dl["k"] == dr["j"]).select("k", "lv", "rv").collect()
        # the real sentinel-valued keys MUST match; nulls must not
        assert out.num_rows == 1
        assert out.column("k").to_pylist() == [SENTINEL]
        assert out.column("lv").to_pylist() == [10]
        assert out.column("rv").to_pylist() == [20]

    def test_cobucketed_join_sentinel_and_null(self, session, tmp_path):
        """Same property through the indexed (co-bucketed) join path."""
        from hyperspace_tpu import constants as C
        from hyperspace_tpu.hyperspace import Hyperspace
        from hyperspace_tpu.indexes.covering import CoveringIndexConfig

        hs = Hyperspace(session)
        left = pa.table(
            {
                "k": pa.array([SENTINEL, 1, None, 5], type=pa.int64()),
                "lv": pa.array([10, 11, 12, 13], type=pa.int64()),
            }
        )
        right = pa.table(
            {
                "j": pa.array([SENTINEL, None, 5, 5], type=pa.int64()),
                "rv": pa.array([20, 21, 22, 23], type=pa.int64()),
            }
        )
        dl = session.read.parquet(_write(tmp_path, "l", left, n_files=2))
        dr = session.read.parquet(_write(tmp_path, "r", right, n_files=2))
        hs.create_index(dl, CoveringIndexConfig("li", ["k"], ["lv"]))
        hs.create_index(dr, CoveringIndexConfig("ri", ["j"], ["rv"]))
        session.enable_hyperspace()
        q = dl.join(dr, on=dl["k"] == dr["j"]).select("k", "lv", "rv")
        plan = q.explain()
        assert plan.count("Hyperspace(Type: CI") == 2
        out = q.collect().sort_by([("rv", "ascending")])
        assert out.column("k").to_pylist() == [SENTINEL, 5, 5]
        assert out.column("rv").to_pylist() == [20, 22, 23]

    def test_groupby_separates_sentinel_from_null(self, session, tmp_path):
        t = pa.table(
            {
                "g": pa.array([SENTINEL, SENTINEL, None, None, 1], pa.int64()),
                "v": pa.array([1, 2, 4, 8, 16], type=pa.int64()),
            }
        )
        df = session.read.parquet(_write(tmp_path, "g", t))
        out = df.group_by("g").agg(F.sum("v").alias("s")).collect()
        got = {
            (g if g is None else int(g)): s
            for g, s in zip(out.column("g").to_pylist(), out.column("s").to_pylist())
        }
        assert got == {SENTINEL: 3, None: 12, 1: 16}


class TestNaNDescending:
    def test_matches_pyarrow_both_directions(self, session, tmp_path):
        t = pa.table(
            {"x": pa.array([1.0, float("nan"), -2.0, None, 5.0, float("nan")])}
        )
        df = session.read.parquet(_write(tmp_path, "n", t))
        for asc, order in ((True, "ascending"), (False, "descending")):
            got = df.sort(("x", asc)).collect().column("x").to_pylist()
            want = t.sort_by([("x", order)]).column("x").to_pylist()
            assert str(got) == str(want), (asc, got, want)


class TestBooleanAggregates:
    def test_sum_avg_bool_rejected_min_max_ok(self, session, tmp_path):
        t = pa.table(
            {
                "b": pa.array([True, False, True]),
                "g": pa.array([1, 1, 2], type=pa.int64()),
            }
        )
        df = session.read.parquet(_write(tmp_path, "b", t))
        with pytest.raises(HyperspaceException, match="sum"):
            df.agg(F.sum("b")).collect()
        with pytest.raises(HyperspaceException, match="avg"):
            df.group_by("g").agg(F.avg("b")).collect()
        out = df.agg(F.min("b").alias("lo"), F.max("b").alias("hi")).collect()
        assert out.column("lo").to_pylist() == [False]
        assert out.column("hi").to_pylist() == [True]


class TestRowGroupPushdown:
    def test_point_filter_pushes_and_matches(self, session, tmp_path, monkeypatch):
        """Simple conjuncts reach pq.read_table as DNF filters (row-group
        pruning on key-sorted index files) and the answer is unchanged."""
        from hyperspace_tpu.hyperspace import Hyperspace
        from hyperspace_tpu.indexes.covering import CoveringIndexConfig
        from hyperspace_tpu.io import parquet as pio

        d = tmp_path / "push"
        d.mkdir()
        rng = np.random.default_rng(6)
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(rng.integers(0, 100, 2000), pa.int64()),
                    "v": pa.array(rng.normal(size=2000)),
                }
            ),
            d / "a.parquet",
        )
        hs = Hyperspace(session)
        df = session.read.parquet(str(d))
        hs.create_index(df, CoveringIndexConfig("pidx", ["k"], ["v"]))
        session.enable_hyperspace()

        captured = []
        real = pio.read_table

        def capture(paths, columns=None, fmt="parquet", filters=None, **kw):
            captured.append(filters)
            return real(paths, columns, fmt, filters, **kw)

        monkeypatch.setattr(
            "hyperspace_tpu.execution.executor.pio.read_table", capture
        )
        q = df.filter(df["k"] == 42).select("k", "v")
        got = q.collect()
        assert any(
            f is not None and ("k", "==", 42) in f for f in captured
        ), captured
        session.disable_hyperspace()
        base = q.collect()
        key = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        assert key(got).equals(key(base)) and got.num_rows > 0

    def test_unsafe_literals_not_pushed(self, session, tmp_path):
        from hyperspace_tpu.execution.executor import _pushdown_filters
        from hyperspace_tpu.plan import expressions as E

        d = tmp_path / "np2"
        d.mkdir()
        pq.write_table(
            pa.table({"s": pa.array(["a", "b"]), "x": pa.array([1, 2])}),
            d / "a.parquet",
        )
        rel = session.read.parquet(str(d)).logical_plan.relation
        # type-mismatched literal on a string column must not be pushed
        assert _pushdown_filters(E.Col("s") == 5, rel) is None
        # null literal must not be pushed
        assert _pushdown_filters(E.Col("x") == None, rel) is None  # noqa: E711
        # valid one is
        assert _pushdown_filters(E.Col("s") == "a", rel) == [("s", "==", "a")]
        # out-of-int64-range int must not be pushed (arrow OverflowError)
        assert _pushdown_filters(E.Col("x") == 2**70, rel) is None
        # bool literal on an int column pushes as its integer value
        assert _pushdown_filters(E.Col("x") == True, rel) == [  # noqa: E712
            ("x", "==", 1)
        ]

    def test_overflow_bool_and_tz_literals_end_to_end(self, session, tmp_path):
        import datetime

        d = tmp_path / "np3"
        d.mkdir()
        ts = pa.array(
            [datetime.datetime(2020, 1, 1), datetime.datetime(2021, 1, 1)],
            type=pa.timestamp("us", tz="UTC"),
        )
        pq.write_table(
            pa.table({"k": pa.array([0, 1], type=pa.int64()), "t": ts}),
            d / "a.parquet",
        )
        df = session.read.parquet(str(d))
        assert df.filter(df["k"] == 2**70).collect().num_rows == 0
        assert df.filter(df["k"] == True).collect().num_rows == 1  # noqa: E712
        # tz-aware column: no push, engine lowers and matches
        got = df.filter(
            df["t"] == datetime.datetime(2020, 1, 1)
        ).collect()
        assert got.num_rows == 1


class TestNaNMinMaxSketch:
    def test_nan_does_not_skip_matching_file(self, session, tmp_path):
        """A NaN in a float column must not poison the file's min/max
        sketch (plain min() returns NaN, making `min <= lit` False and
        wrongly pruning a file that has matching rows)."""
        from hyperspace_tpu.hyperspace import Hyperspace
        from hyperspace_tpu.indexes.dataskipping import DataSkippingIndexConfig
        from hyperspace_tpu.indexes.sketches import MinMaxSketch

        d = tmp_path / "nansketch"
        d.mkdir()
        pq.write_table(
            pa.table({"x": pa.array([1.0, 2.0, float("nan")])}),
            d / "a.parquet",
        )
        pq.write_table(
            pa.table({"x": pa.array([100.0, 200.0])}), d / "b.parquet"
        )
        hs = Hyperspace(session)
        df = session.read.parquet(str(d))
        hs.create_index(df, DataSkippingIndexConfig("nsk", MinMaxSketch("x")))
        session.enable_hyperspace()
        out = df.filter(df["x"] <= 2.0).select("x").collect()
        assert sorted(out.column("x").to_pylist()) == [1.0, 2.0]


class TestTemporalLiterals:
    """Date/timestamp literals in predicates lower to the column's int64
    storage units — identically on the host and device filter paths."""

    @pytest.fixture
    def dated(self, session, tmp_path):
        d = tmp_path / "dated"
        d.mkdir()
        base = np.datetime64("1994-01-01")
        dates = (base + np.arange(1000).astype("timedelta64[D]")).astype(
            "datetime64[D]"
        )
        ts = dates.astype("datetime64[us]")
        pq.write_table(
            pa.table(
                {
                    "d": pa.array(dates),
                    "ts": pa.array(ts),
                    "v": pa.array(np.arange(1000), type=pa.int64()),
                }
            ),
            d / "a.parquet",
        )
        return session.read.parquet(str(d))

    def test_date_range_filter(self, dated):
        import datetime

        out = dated.filter(
            dated["d"] >= np.datetime64("1996-01-01")
        ).select("d", "v")
        got = out.collect()
        assert got.num_rows == 270
        assert min(got.column("d").to_pylist()) == datetime.date(1996, 1, 1)

    def test_date_literal_spellings_agree(self, dated):
        import datetime

        for lit in (
            np.datetime64("1995-03-01"),
            datetime.date(1995, 3, 1),
            "1995-03-01",
        ):
            got = dated.filter(dated["d"] == lit).collect()
            assert got.num_rows == 1, lit

    def test_date_literal_on_timestamp_column(self, dated):
        import datetime

        got = dated.filter(
            dated["ts"] == datetime.date(1995, 3, 1)
        ).collect()
        assert got.num_rows == 1

    def test_date_in_list(self, dated):
        got = dated.filter(
            dated["d"].isin(
                np.datetime64("1994-02-01"), np.datetime64("1994-03-01")
            )
        ).collect()
        assert got.num_rows == 2

    def test_unrepresentable_literal(self, dated):
        assert dated.filter(dated["d"] == "not-a-date").collect().num_rows == 0
        out = dated.filter(dated["d"] != "not-a-date").collect()
        assert out.num_rows == 1000

    def test_out_of_range_literal_orders_correctly(self, session, tmp_path):
        """A literal beyond the column unit's int64 range clamps to ±inf:
        orderings keep their definite answer instead of silently matching
        nothing (numpy overflow used to wrap)."""
        d = tmp_path / "ns"
        d.mkdir()
        ts = np.array(
            ["2020-01-01T00:00:00", "2021-01-01T00:00:00"],
            dtype="datetime64[ns]",
        )
        pq.write_table(pa.table({"ts": pa.array(ts)}), d / "a.parquet")
        df = session.read.parquet(str(d))
        # 2300 overflows int64 nanoseconds (max ~2262): all rows are below
        assert df.filter(df["ts"] < np.datetime64("2300-01-01")).collect().num_rows == 2
        assert df.filter(df["ts"] > np.datetime64("2300-01-01")).collect().num_rows == 0
        assert df.filter(df["ts"] == np.datetime64("2300-01-01")).collect().num_rows == 0

    def test_sub_tick_literal_orders_correctly(self, session, tmp_path):
        """A ns-precision literal between two µs column ticks keeps exact
        ordering answers (lowered to tick+0.5, never equal, orders right)."""
        d = tmp_path / "us"
        d.mkdir()
        ts = np.array(
            ["2020-01-01T00:00:00.000001", "2020-01-01T00:00:00.000002"],
            dtype="datetime64[us]",
        )
        pq.write_table(pa.table({"ts": pa.array(ts)}), d / "a.parquet")
        df = session.read.parquet(str(d))
        mid = np.datetime64("2020-01-01T00:00:00.000001500", "ns")
        assert df.filter(df["ts"] < mid).collect().num_rows == 1
        assert df.filter(df["ts"] > mid).collect().num_rows == 1
        assert df.filter(df["ts"] == mid).collect().num_rows == 0
        # and an IN list containing it can never match (no float upcast
        # false positives)
        assert df.filter(df["ts"].isin(mid)).collect().num_rows == 0

    def test_time_columns_roundtrip_and_filter(self, session, tmp_path):
        """time32/time64 columns ingest (as their integer representation),
        round-trip, and compare against datetime.time / ISO literals."""
        import datetime

        d = tmp_path / "times"
        d.mkdir()
        t64 = pa.array(
            [datetime.time(9, 0), datetime.time(12, 30), datetime.time(18, 45)],
            type=pa.time64("us"),
        )
        t32 = pa.array(
            [datetime.time(1, 0), None, datetime.time(23, 59)],
            type=pa.time32("s"),
        )
        pq.write_table(
            pa.table({"a": t64, "b": t32, "v": pa.array([1, 2, 3], pa.int64())}),
            d / "x.parquet",
        )
        df = session.read.parquet(str(d))
        out = df.collect()
        assert out.column("a").to_pylist() == t64.to_pylist()
        assert out.column("b").to_pylist() == t32.to_pylist()
        assert df.filter(df["a"] == datetime.time(12, 30)).collect().num_rows == 1
        assert df.filter(df["a"] > datetime.time(10, 0)).collect().num_rows == 2
        assert df.filter(df["a"] <= "12:30:00").collect().num_rows == 2
        assert df.filter(df["b"] < datetime.time(2, 0)).collect().num_rows == 1
        # between-tick on a seconds column: 01:00:00.5 lies between ticks
        assert df.filter(
            df["b"] <= datetime.time(1, 0, 0, 500000)
        ).collect().num_rows == 1
        assert df.filter(
            df["b"] == datetime.time(1, 0, 0, 500000)
        ).collect().num_rows == 0
        # zoned time-of-day has no date to anchor a conversion: never matches
        zoned = datetime.time(12, 30, tzinfo=datetime.timezone.utc)
        assert df.filter(df["a"] == zoned).collect().num_rows == 0
        # layout analysis handles time columns (footer stats are time objs)
        from hyperspace_tpu.plananalysis.minmax_analysis import analyze_min_max

        res = analyze_min_max(df, ["a", "b"])
        assert all(r.max_files_per_lookup == 1 for r in res)

    def test_numpy_scalar_in_list(self, session, tmp_path):
        """isin(np.int64(5)) must behave like == np.int64(5)."""
        d = tmp_path / "npscalar"
        d.mkdir()
        pq.write_table(
            pa.table({"k": pa.array([3, 5, 7], type=pa.int64())}),
            d / "a.parquet",
        )
        df = session.read.parquet(str(d))
        lit = np.int64(5)
        assert df.filter(df["k"].isin(lit)).collect().num_rows == 1
        assert df.filter(df["k"] == lit).collect().num_rows == 1

    def test_between_tick_ordering_far_future(self, session, tmp_path):
        """Between-tick literals keep exact ordering even beyond float53
        epochs (op-aware integer boundary, no float rounding)."""
        d = tmp_path / "far"
        d.mkdir()
        ts = np.array(
            ["2260-01-01T00:00:00", "2262-01-01T00:00:00"],
            dtype="datetime64[us]",
        )
        pq.write_table(pa.table({"ts": pa.array(ts)}), d / "a.parquet")
        df = session.read.parquet(str(d))
        mid = np.datetime64("2261-01-01T00:00:00.000000500", "ns")
        assert df.filter(df["ts"] < mid).collect().num_rows == 1
        assert df.filter(df["ts"] >= mid).collect().num_rows == 1
        assert df.filter(df["ts"] == mid).collect().num_rows == 0

    def test_not_unrepresentable_excludes_nulls_both_paths(
        self, session, tmp_path
    ):
        """~(col == <garbage>) must exclude null rows identically on the
        host evaluator and the device filter."""
        from hyperspace_tpu.io.columnar import ColumnarBatch
        from hyperspace_tpu.ops.filter import device_filter_mask
        from hyperspace_tpu.plan import expressions as E

        import datetime

        d = tmp_path / "nn"
        d.mkdir()
        pq.write_table(
            pa.table(
                {"d": pa.array([datetime.date(2020, 1, 1), None], type=pa.date32())}
            ),
            d / "a.parquet",
        )
        df = session.read.parquet(str(d))
        cond = ~(E.Col("d") == "not-a-date")
        batch = ColumnarBatch.from_arrow(df.collect())
        host = E.filter_mask(cond, batch)
        dev = device_filter_mask(cond, batch)
        assert host.tolist() == [True, False]
        assert dev.tolist() == host.tolist()


class TestLimitPushdown:
    def test_limit_reads_only_needed_files(self, session, tmp_path, monkeypatch):
        t = pa.table({"x": pa.array(np.arange(1000), type=pa.int64())})
        d = _write(tmp_path, "lim", t, n_files=10)
        df = session.read.parquet(d)

        from hyperspace_tpu.io import parquet as pio

        seen = []
        real = pio.read_table

        def counting(paths, columns=None, fmt="parquet", filters=None, **kw):
            seen.extend(paths)
            return real(paths, columns, fmt, filters, **kw)

        monkeypatch.setattr(
            "hyperspace_tpu.execution.executor.pio.read_table", counting
        )
        out = df.limit(5).collect()
        assert out.num_rows == 5
        # naive execution reads all 10 files; streaming stops at the first
        assert len(seen) == 1
        # the result is the same prefix the full read produces
        assert out.column("x").to_pylist() == list(range(5))

    def test_limit_through_filter_and_project(self, session, tmp_path):
        t = pa.table({"x": pa.array(np.arange(100), type=pa.int64())})
        d = _write(tmp_path, "limf", t, n_files=5)
        df = session.read.parquet(d)
        out = df.filter(df["x"] >= 50).select("x").limit(3).collect()
        assert out.column("x").to_pylist() == [50, 51, 52]

    def test_limit_over_sort_is_topn(self, session, tmp_path):
        rng = np.random.default_rng(3)
        t = pa.table({"x": pa.array(rng.permutation(200), type=pa.int64())})
        d = _write(tmp_path, "lims", t, n_files=4)
        df = session.read.parquet(d)
        out = df.sort(("x", False)).limit(4).collect()
        assert out.column("x").to_pylist() == [199, 198, 197, 196]


class TestRowLevelPushdownSuperset:
    """Pushed filters must keep a ROW-LEVEL superset of engine-matching
    rows — pyarrow >= 14 applies pq.read_table filters per row (dataset
    API), not merely per row group (io/parquet.read_table invariant).
    Pins the conjunct classes whose row-level semantics could diverge."""

    def _roundtrip(self, session, tmp_path, table, q):
        """collect() with normal pushdown vs pushdown force-disabled."""
        import hyperspace_tpu.execution.executor as X

        d = tmp_path / "rl"
        d.mkdir(exist_ok=True)
        pq.write_table(table, d / "a.parquet")
        df = session.read.parquet(str(d))
        with_push = q(df).collect()
        real = X._pushdown_filters
        X._pushdown_filters = lambda cond, rel: None
        try:
            without = q(df).collect()
        finally:
            X._pushdown_filters = real
        key = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        assert key(with_push).equals(key(without))
        return with_push

    def test_between_tick_timestamp_not_pushed(self, session, tmp_path):
        from hyperspace_tpu.execution.executor import _pushable_literal

        # a microsecond literal against a SECOND-resolution column is not
        # exactly representable: pushing it would let arrow's cast choose
        # a rounding the engine does not use — it must be refused
        lit = np.datetime64("2020-01-01T00:00:00.5", "us")
        assert _pushable_literal(lit, pa.timestamp("s")) is None
        t = pa.table(
            {
                "ts": pa.array(
                    np.array(
                        ["2020-01-01T00:00:00", "2020-01-01T00:00:01"],
                        dtype="datetime64[s]",
                    )
                ),
                "v": pa.array([1, 2], pa.int64()),
            }
        )
        out = self._roundtrip(
            session, tmp_path, t,
            lambda df: df.filter(df["ts"] == lit).select("v"),
        )
        assert out.num_rows == 0  # engine: between-tick literal never matches

    def test_negative_zero_and_nan_equality(self, session, tmp_path):
        t = pa.table(
            {
                "x": pa.array([0.0, -0.0, float("nan"), 1.0]),
                "v": pa.array([1, 2, 3, 4], pa.int64()),
            }
        )
        out = self._roundtrip(
            session, tmp_path, t,
            lambda df: df.filter(df["x"] == 0.0).select("v"),
        )
        # IEEE: -0.0 == 0.0 matches; NaN never does — in BOTH engines
        assert sorted(out.column("v").to_pylist()) == [1, 2]

    def test_out_of_int64_range_literal_not_pushed(self, session, tmp_path):
        from hyperspace_tpu.execution.executor import _pushable_literal

        assert _pushable_literal(2**63, pa.int64()) is None
        t = pa.table({"k": pa.array([1, 2], pa.int64())})
        out = self._roundtrip(
            session, tmp_path, t,
            lambda df: df.filter(df["k"] == 2**63).select("k"),
        )
        assert out.num_rows == 0


class TestDurationLiterals:
    """Duration (interval) literal lowering — round-5 closure of the known
    predicate hole (reference: Catalyst's interval casts)."""

    def _table(self):
        return pa.table(
            {
                "d": pa.array(
                    np.array([1000, 2500, -3000, 0], dtype="timedelta64[ms]")
                ),
                "v": pa.array([1, 2, 3, 4], pa.int64()),
            }
        )

    def _q(self, session, tmp_path, q):
        d = tmp_path / "dur"
        d.mkdir(exist_ok=True)
        pq.write_table(self._table(), d / "a.parquet")
        return q(session.read.parquet(str(d))).collect()

    def test_matching_unit_equality(self, session, tmp_path):
        out = self._q(
            session, tmp_path,
            lambda df: df.filter(
                df["d"] == np.timedelta64(2500, "ms")
            ).select("v"),
        )
        assert out.column("v").to_pylist() == [2]

    def test_finer_unit_between_ticks(self, session, tmp_path):
        # 2500500us is between ms ticks: equality never matches; the range
        # comparison keeps exactly the values strictly below it
        lit = np.timedelta64(2_500_500, "us")
        eq = self._q(
            session, tmp_path,
            lambda df: df.filter(df["d"] == lit).select("v"),
        )
        assert eq.num_rows == 0
        lt = self._q(
            session, tmp_path,
            lambda df: df.filter(df["d"] < lit).select("v"),
        )
        assert sorted(lt.column("v").to_pylist()) == [1, 2, 3, 4]

    def test_python_timedelta_and_negative(self, session, tmp_path):
        import datetime

        out = self._q(
            session, tmp_path,
            lambda df: df.filter(
                df["d"] < datetime.timedelta(seconds=0)
            ).select("v"),
        )
        assert out.column("v").to_pylist() == [3]

    def test_calendar_units_never_match(self, session, tmp_path):
        # numpy Y/M timedeltas are calendar-length (no fixed ns value):
        # the engine refuses them — equality never matches
        out = self._q(
            session, tmp_path,
            lambda df: df.filter(
                df["d"] == np.timedelta64(1, "M")
            ).select("v"),
        )
        assert out.num_rows == 0

    def test_overflow_clamps_not_wraps(self, session, tmp_path):
        # a duration beyond int64 ticks of the COLUMN unit must clamp to
        # +inf (all rows compare smaller), never wrap negative: 9e15 days
        # = 7.8e23 ms >> int64 max (9.2e18)
        big = np.timedelta64(9_000_000_000_000_000, "D")
        out = self._q(
            session, tmp_path,
            lambda df: df.filter(df["d"] < big).select("v"),
        )
        assert out.num_rows == 4

    def test_duration_roundtrip_to_arrow(self):
        from hyperspace_tpu.io.columnar import ColumnarBatch

        t = self._table()
        assert ColumnarBatch.from_arrow(t).to_arrow().equals(t)

    def test_duration_filters_not_pushed(self):
        from hyperspace_tpu.execution.executor import _pushable_literal

        assert _pushable_literal(np.timedelta64(1, "s"), pa.duration("ms")) is None

    def test_nat_duration_never_matches(self, session, tmp_path):
        # NaT's int64 view is int64-min; treating it as a tick count would
        # make >= NaT match every row — numpy/pyarrow both say none
        nat = np.timedelta64("NaT", "ms")
        for q in (
            lambda df: df.filter(df["d"] >= nat).select("v"),
            lambda df: df.filter(df["d"] == nat).select("v"),
            lambda df: df.filter(df["d"] < nat).select("v"),
        ):
            out = self._q(session, tmp_path, q)
            assert out.num_rows == 0


class TestParquetDictionaryGate:
    """The dictionary opt-out gate must sample ACROSS the table: index
    tables arrive key-sorted, so a prefix sample sees only the clustered
    duplicates of the first few keys and would re-enable dictionary
    encoding for globally high-cardinality key columns."""

    def test_sorted_key_column_skips_dictionary(self):
        import numpy as np
        import pyarrow as pa

        from hyperspace_tpu.io.parquet import _dictionary_columns

        n = 400_000
        # each key appears 8x, keys sorted: prefix of 4096 rows has only
        # 512 distinct values, but globally there are 50k distinct
        key = np.repeat(np.arange(n // 8, dtype=np.int64), 8)
        low = np.tile(np.arange(30, dtype=np.int64), n // 30 + 1)[:n]
        t = pa.table({"key": key, "day": low, "s": pa.array(["x"] * n)})
        cols = _dictionary_columns(t)
        assert "key" not in cols           # high-cardinality: no dict
        assert "day" in cols               # low-cardinality: keep dict
        assert "s" in cols                 # strings always keep dict

    def test_empty_and_small_tables(self):
        import numpy as np
        import pyarrow as pa

        from hyperspace_tpu.io.parquet import _dictionary_columns

        empty = pa.table({"a": pa.array([], pa.int64())})
        assert _dictionary_columns(empty) is False
        small = pa.table({"a": np.zeros(100, dtype=np.int64)})
        assert _dictionary_columns(small) == ["a"]
