"""Replicated serve fleet (docs/fleet-serve.md): durable pins, version
fanout, cross-process single-flight, per-tenant SLO classes.

The durable-pin × GC/vacuum interaction lives in
``tests/test_crash_recovery.py`` (``TestCrossProcessPins``); this file
covers the serve-tier planes — the bus, the claim/spool single-flight
(driven through two in-process ``FleetFrontend`` instances, which share
NO in-process state by construction, so the file protocol is what
coordinates them), the SLO-class scheduler, and (slow) the real
multi-process harness with its kill -9 rung.
"""

import json
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import functions as F
from hyperspace_tpu.exceptions import ServeOverloadedError
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.serve.bus import FleetBus
from hyperspace_tpu.serve.fleet import FleetFrontend, spool_dir
from hyperspace_tpu.serve.frontend import ServeFrontend


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


@pytest.fixture
def fleet_env(tmp_path):
    """One lake + two fleet sessions over it (the in-process stand-in
    for two frontend processes: separate sessions, separate caches,
    coordination only through the lake's files)."""
    from hyperspace_tpu.session import HyperspaceSession

    src = tmp_path / "src"
    src.mkdir()
    rng = np.random.default_rng(7)
    n = 4000
    pq.write_table(
        pa.table(
            {
                "k": pa.array(rng.integers(0, 60, n), pa.int64()),
                "v": pa.array(rng.integers(-500, 500, n), pa.int64()),
            }
        ),
        str(src / "part-0.parquet"),
    )
    index_root = str(tmp_path / "indexes")

    def make_session(**conf):
        s = HyperspaceSession()
        s.conf.set(C.INDEX_SYSTEM_PATH, index_root)
        s.conf.set(C.INDEX_NUM_BUCKETS, 4)
        s.conf.set(C.FLEET_ENABLED, True)
        s.conf.set(C.SERVE_CACHE_ENABLED, True)
        s.conf.set(C.FLEET_BUS_POLL_MS, 20)
        for k, v in conf.items():
            s.conf.set(k, v)
        s.enable_hyperspace()
        return s

    s1 = make_session()
    hs1 = Hyperspace(s1)
    df = s1.read.parquet(str(src))
    hs1.create_index(df, CoveringIndexConfig("fidx", ["k"], ["v"]))
    return {
        "src": str(src),
        "index_root": index_root,
        "make_session": make_session,
        "s1": s1,
        "hs1": hs1,
        "rng": rng,
    }


# ---------------------------------------------------------------------------
# The fanout bus
# ---------------------------------------------------------------------------


class TestFleetBus:
    def test_publish_poll_roundtrip(self, tmp_path):
        d = str(tmp_path / "bus")
        a = FleetBus(d, retain_ms=60_000)
        b = FleetBus(d, retain_ms=60_000)
        b.prime()
        a.publish({"type": "index_changed", "root": "/x"})
        a.publish({"type": "index_changed", "root": "/y"})
        events = b.poll_once()
        assert [e["root"] for e in events] == ["/x", "/y"]
        assert b.poll_once() == []  # seen once
        assert b.received == 2

    def test_own_events_skipped(self, tmp_path):
        d = str(tmp_path / "bus")
        a = FleetBus(d)
        a.prime()
        a.publish({"type": "index_changed", "root": "/x"})
        assert a.poll_once() == []

    def test_prime_skips_history(self, tmp_path):
        d = str(tmp_path / "bus")
        a = FleetBus(d)
        a.publish({"type": "index_changed", "root": "/old"})
        b = FleetBus(d)
        b.prime()
        assert b.poll_once() == []
        a.publish({"type": "index_changed", "root": "/new"})
        assert [e["root"] for e in b.poll_once()] == ["/new"]

    def test_retention_prune(self, tmp_path):
        d = str(tmp_path / "bus")
        a = FleetBus(d, retain_ms=80)
        a.publish({"type": "index_changed", "root": "/x"})
        time.sleep(0.15)
        a.publish({"type": "index_changed", "root": "/y"})
        assert a.pruned >= 1
        names = [f for f in os.listdir(d) if f.endswith(".json")]
        assert len(names) == 1

    def test_torn_event_skipped(self, tmp_path):
        d = str(tmp_path / "bus")
        os.makedirs(d)
        b = FleetBus(d)
        b.prime()
        with open(os.path.join(d, "9999999999999.dead.000001.json"), "w") as f:
            f.write('{"type": "ind')
        assert b.poll_once() == []

    def test_subscriber_thread_delivers(self, tmp_path):
        d = str(tmp_path / "bus")
        got = []
        done = threading.Event()
        b = FleetBus(d, poll_ms=10)
        b.start(lambda e: (got.append(e), done.set()))
        try:
            FleetBus(d).publish({"type": "index_changed", "root": "/z"})
            assert done.wait(5.0)
            assert got[0]["root"] == "/z"
        finally:
            b.stop()


# ---------------------------------------------------------------------------
# ServeCache fanout eviction
# ---------------------------------------------------------------------------


class TestEvictPathsUnder:
    def test_evicts_only_matching_index(self):
        from hyperspace_tpu.execution.serve_cache import ServeCache

        c = ServeCache(1 << 20)
        fp_a = (("/lake/idxA/v__=1/part-0.parquet", 10, 1),)
        fp_b = (("/lake/idxB/v__=1/part-0.parquet", 10, 1),)
        c.put(("scan", fp_a), "a", 10)
        c.put(("zonemap", fp_a), "za", 10)
        c.put(("joinside", (fp_a, fp_b), ("k",), ("k",)), "j", 10)
        c.put(("scan", fp_b), "b", 10)
        assert c.evict_paths_under("/lake/idxA") == 3
        assert c.get(("scan", fp_b)) == "b"
        assert c.get(("scan", fp_a)) is None
        assert c.resident_bytes == 10


# ---------------------------------------------------------------------------
# Aggstate push payloads (ROADMAP 2c)
# ---------------------------------------------------------------------------


class TestAggstatePush:
    def test_payload_roundtrip(self, fleet_env):
        from hyperspace_tpu.execution.serve_cache import ServeCache
        from hyperspace_tpu.indexes import aggindex

        s1 = fleet_env["s1"]
        entries = s1.index_manager.get_indexes([C.States.ACTIVE])
        files = entries[0].content.files
        payload = aggindex.fanout_payload(files)
        assert payload is not None
        # JSON round trip, as the bus would carry it
        payload = json.loads(json.dumps(payload))
        cache = ServeCache(1 << 24)
        aggindex.invalidate_local_cache()
        assert aggindex.install_fanout_payload(payload, cache)
        assert cache.bytes_by_kind().get("aggstate", 0) > 0

    def test_stale_payload_dropped(self, fleet_env):
        from hyperspace_tpu.indexes import aggindex

        s1 = fleet_env["s1"]
        entries = s1.index_manager.get_indexes([C.States.ACTIVE])
        payload = aggindex.fanout_payload(entries[0].content.files)
        payload["fp"][0][1] += 1  # stats moved on: stale push
        assert not aggindex.install_fanout_payload(payload, None)

    def test_refresh_fans_out_to_peer(self, fleet_env):
        src, rng = fleet_env["src"], fleet_env["rng"]
        s2 = fleet_env["make_session"]()
        fe2 = s2.serve_frontend
        try:
            assert isinstance(fe2, FleetFrontend)
            pq.write_table(
                pa.table(
                    {
                        "k": pa.array(rng.integers(0, 60, 500), pa.int64()),
                        "v": pa.array(
                            rng.integers(-500, 500, 500), pa.int64()
                        ),
                    }
                ),
                os.path.join(src, "part-1.parquet"),
            )
            fleet_env["hs1"].refresh_index("fidx", "incremental")
            # wait on bus_installed, not bus_events: the callback counts
            # the event BEFORE it installs the payload
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                st = fe2.stats()["fleet"]
                if st["bus_installed"] >= 1:
                    break
                time.sleep(0.02)
            st = fe2.stats()["fleet"]
            assert st["bus_events"] >= 1, st
            assert st["bus_installed"] >= 1, st
            # the peer serves the NEW snapshot correctly
            df = s2.read.parquet(src)
            q = df.filter(df["k"] >= 10).agg(F.count().alias("n"))
            got = fe2.serve(q)
            s2.disable_hyperspace()
            want = q.collect()
            s2.enable_hyperspace()
            assert got.equals(want)
        finally:
            fe2.close()


# ---------------------------------------------------------------------------
# Cross-process single-flight (claim + spool)
# ---------------------------------------------------------------------------


class TestSingleFlight:
    """The durable claim/spool plane, tested in isolation: fast routing
    OFF, so every dedup goes through the claim election (the plane the
    fast path degrades to — it must keep working on its own)."""

    def test_two_frontends_one_execution(self, fleet_env):
        s1 = fleet_env["make_session"](**{C.FLEET_FAST_ENABLED: False})
        s2 = fleet_env["make_session"](**{C.FLEET_FAST_ENABLED: False})
        fe1, fe2 = s1.serve_frontend, s2.serve_frontend
        try:
            src = fleet_env["src"]
            q1 = s1.read.parquet(src)
            q1 = q1.filter(q1["k"] == 11)
            q2 = s2.read.parquet(src)
            q2 = q2.filter(q2["k"] == 11)
            t1 = fe1.serve(q1)
            t2 = fe2.serve(q2)
            assert sorted_table(t1).equals(sorted_table(t2))
            st1, st2 = fe1.stats()["fleet"], fe2.stats()["fleet"]
            assert st1["claims_won"] + st2["claims_won"] == 1
            assert st1["spool_hits"] + st2["spool_hits"] == 1
            # the election telemetry agrees with the outcome
            assert st1["election_wins"] + st2["election_wins"] == 1
            assert (
                st1["election_attempts"] + st2["election_attempts"] >= 1
            )
            # the answer is correct vs the unindexed truth
            s1.disable_hyperspace()
            want = q1.collect()
            s1.enable_hyperspace()
            assert sorted_table(t1).equals(sorted_table(want))
        finally:
            fe1.close()
            fe2.close()

    def test_expired_claim_taken_over(self, fleet_env):
        s2 = fleet_env["make_session"]()
        s2.conf.set(C.FLEET_SINGLEFLIGHT_CLAIM_MS, 30)
        fe2 = s2.serve_frontend
        try:
            # a dead winner's claim (kill -9 mid-serve) sits in the
            # spool; its lease expires and fe2 takes the claim over
            claim = os.path.join(spool_dir(s2.conf), "deadbeef.claim")
            os.makedirs(os.path.dirname(claim), exist_ok=True)
            with open(claim, "w") as f:
                json.dump({"owner": "dead", "expiresAtMs": 1}, f)
            assert fe2._try_claim(claim) == "won"
            # a LIVE claim is respected
            claim2 = os.path.join(spool_dir(s2.conf), "cafebabe.claim")
            with open(claim2, "w") as f:
                json.dump(
                    {
                        "owner": "live",
                        "expiresAtMs": int(time.time() * 1000) + 600_000,
                    },
                    f,
                )
            assert fe2._try_claim(claim2) == "held"
        finally:
            fe2.close()

    def test_wait_timeout_executes_locally(self, fleet_env):
        s2 = fleet_env["make_session"](**{C.FLEET_FAST_ENABLED: False})
        s2.conf.set(C.FLEET_SINGLEFLIGHT_WAIT_MS, 50)
        s2.conf.set(C.FLEET_SINGLEFLIGHT_CLAIM_MS, 600_000)
        fe2 = s2.serve_frontend
        try:
            src = fleet_env["src"]
            q = s2.read.parquet(src)
            q = q.filter(q["k"] == 31)
            pin = fe2._pin()
            digest = fe2._plan_digest(q.logical_plan, pin)
            claim = os.path.join(spool_dir(s2.conf), digest + ".claim")
            os.makedirs(os.path.dirname(claim), exist_ok=True)
            with open(claim, "w") as f:
                json.dump(
                    {
                        "owner": "live-elsewhere",
                        "expiresAtMs": int(time.time() * 1000) + 600_000,
                    },
                    f,
                )
            t = fe2.serve(q)  # waits 50ms, then serves locally
            s2.disable_hyperspace()
            want = q.collect()
            s2.enable_hyperspace()
            assert sorted_table(t).equals(sorted_table(want))
            st = fe2.stats()["fleet"]
            assert st["singleflight_local"] >= 1, st
            assert st["claim_waits"] >= 1, st
            # the held claim shows up as election losses, and the
            # backoff means a 50ms wait attempts only a few elections
            # (not 50ms / 10ms-poll fixed-cadence hammering)
            assert st["election_losses"] >= 1, st
            assert st["election_wins"] == 0, st
        finally:
            fe2.close()

    def test_spool_prune_respects_budget(self, fleet_env):
        s2 = fleet_env["make_session"](**{C.FLEET_FAST_ENABLED: False})
        s2.conf.set(C.FLEET_SPOOL_MAX_BYTES, 1)
        fe2 = s2.serve_frontend
        try:
            src = fleet_env["src"]
            q = s2.read.parquet(src)
            q = q.filter(q["k"] == 42)
            fe2.serve(q)
            sd = spool_dir(s2.conf)
            arrows = [f for f in os.listdir(sd) if f.endswith(".arrow")]
            assert arrows == []  # over-budget results pruned immediately
        finally:
            fe2.close()


# ---------------------------------------------------------------------------
# The fast data plane: push bus + owner routing (hyperspace.fleet.fast.*)
# ---------------------------------------------------------------------------


def _query_owned_by(fe, session, src, target_owner):
    """A probe DataFrame whose (plan, snapshot) digest rendezvous-routes
    to ``target_owner`` (searched over a predicate family disjoint from
    the other tests' plans)."""
    from hyperspace_tpu.serve.router import rendezvous_owner

    members = fe._router.members(refresh=True)
    pin = fe._pin()
    for kk in range(300):
        df = session.read.parquet(src)
        df = df.filter((df["k"] == kk % 60) & (df["v"] > -(10**6) - kk))
        digest = fe._plan_digest(df.logical_plan, pin)
        if rendezvous_owner(members.keys(), digest) == target_owner:
            return df, digest
    raise AssertionError(f"no probe routed to {target_owner}")


class TestFastPath:
    def test_owner_local_serve_skips_claim_election(self, fleet_env):
        s = fleet_env["make_session"]()
        fe = s.serve_frontend
        try:
            assert fe._router is not None  # the fast plane came up
            src = fleet_env["src"]
            q = s.read.parquet(src)
            q = q.filter(q["k"] == 13)
            t1 = fe.serve(q)
            # sole member: every digest routes to self — served through
            # the in-memory single-flight, no claim file, no election
            st = fe.stats()["fleet"]
            assert st["election_attempts"] == 0, st
            assert st["claims_won"] == 0, st
            sd = spool_dir(s.conf)
            if os.path.isdir(sd):
                assert [f for f in os.listdir(sd) if f.endswith(".claim")] == []
            # the repeat serve is an in-memory result-cache hit
            q2 = s.read.parquet(src)
            q2 = q2.filter(q2["k"] == 13)
            t2 = fe.serve(q2)
            assert sorted_table(t1).equals(sorted_table(t2))
            assert fe.stats()["fleet"]["fast_result_hits"] >= 1
            # ...and the owner's result still reaches the durable spool
            # (async) for cross-host peers and crash recovery
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if fe.stats()["fleet"]["spool_publishes"] >= 1:
                    break
                time.sleep(0.02)
            assert fe.stats()["fleet"]["spool_publishes"] >= 1
        finally:
            fe.close()

    def test_remote_handoff_skips_spool(self, fleet_env):
        s1 = fleet_env["make_session"]()
        s2 = fleet_env["make_session"]()
        fe1, fe2 = s1.serve_frontend, s2.serve_frontend
        try:
            src = fleet_env["src"]
            q, _d = _query_owned_by(fe1, s1, src, fe2._router.owner)
            t = fe1.serve(q)
            st1, st2 = fe1.stats()["fleet"], fe2.stats()["fleet"]
            # the requester streamed the answer straight from the owner:
            # no claim election, no spool read, anywhere
            assert st1["fast_handoffs"] == 1, st1
            assert st2["fast_requests_served"] == 1, st2
            assert st1["claims_won"] + st2["claims_won"] == 0
            assert st1["spool_hits"] + st2["spool_hits"] == 0
            # bit-identical vs the unindexed truth
            s1.disable_hyperspace()
            want = q.collect()
            s1.enable_hyperspace()
            assert sorted_table(t).equals(sorted_table(want))
        finally:
            fe1.close()
            fe2.close()

    def test_refresh_push_beats_poll(self, fleet_env):
        # a refresh's fanout is PUSHED to the peer's socket (microsecond
        # delivery) and the durable poll then dedups it by event name
        src, rng = fleet_env["src"], fleet_env["rng"]
        s2 = fleet_env["make_session"](**{C.FLEET_BUS_POLL_MS: 60_000})
        fe2 = s2.serve_frontend
        try:
            pq.write_table(
                pa.table(
                    {
                        "k": pa.array(rng.integers(0, 60, 300), pa.int64()),
                        "v": pa.array(
                            rng.integers(-500, 500, 300), pa.int64()
                        ),
                    }
                ),
                os.path.join(src, "part-push.parquet"),
            )
            fleet_env["hs1"].refresh_index("fidx", "incremental")
            # the poll plane is parked for 60s: only the push can
            # deliver this fast
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if fe2.stats()["fleet"]["fast_push_received"] >= 1:
                    break
                time.sleep(0.01)
            st = fe2.stats()["fleet"]
            assert st["fast_push_received"] >= 1, st
            assert st["bus_events"] >= 1, st
        finally:
            fe2.close()

    def test_dead_owner_falls_back_bit_identical(self, fleet_env):
        # the in-process twin of the harness's kill -9 probe: the
        # owner's socket dies (member file stays — lease not expired),
        # the requester's fast path fails, the durable claim plane
        # answers, and the answer is bit-identical to the truth
        s1 = fleet_env["make_session"]()
        s2 = fleet_env["make_session"]()
        fe1, fe2 = s1.serve_frontend, s2.serve_frontend
        try:
            src = fleet_env["src"]
            q, _d = _query_owned_by(fe1, s1, src, fe2._router.owner)
            fe2._router._server.stop()  # kill the socket, keep the lease
            t = fe1.serve(q)
            st1 = fe1.stats()["fleet"]
            assert st1["fast_fallbacks"] == 1, st1
            assert st1["claims_won"] == 1, st1  # durable election won
            s1.disable_hyperspace()
            want = q.collect()
            s1.enable_hyperspace()
            assert sorted_table(t).equals(sorted_table(want))
        finally:
            fe1.close()
            fe2.close()

    def test_owner_verifies_digest_before_answering(self, fleet_env):
        # the fast-path correctness invariant: an owner whose snapshot
        # disagrees with the requested digest replies miss, never an
        # answer to a different question
        s1 = fleet_env["make_session"]()
        fe1 = s1.serve_frontend
        try:
            from hyperspace_tpu.obs import planspec
            from hyperspace_tpu.serve import fastbus

            src = fleet_env["src"]
            df = s1.read.parquet(src)
            df = df.filter(df["k"] == 7)
            spec = planspec.to_spec(df.logical_plan)
            reply, body = fastbus.request(
                fe1._router._server.path,
                {"type": "exec", "digest": "f" * 40, "spec": spec},
            )
            assert reply["status"] == "miss", reply
            assert reply["reason"] == "snapshot"
            assert body == b""
        finally:
            fe1.close()

    def test_member_files_reaped(self, tmp_path):
        from hyperspace_tpu.serve import router as fleet_router

        d = str(tmp_path / "members")
        os.makedirs(d)
        now = int(time.time() * 1000)
        # expired lease: reaped (socket file too)
        sock = str(tmp_path / "dead.sock")
        with open(sock, "w") as f:
            f.write("")
        with open(os.path.join(d, "aa.json"), "w") as f:
            json.dump(
                {"owner": "aa", "pid": 1, "sock": sock, "expiresAtMs": 1}, f
            )
        # live lease, live pid: kept
        with open(os.path.join(d, "bb.json"), "w") as f:
            json.dump(
                {
                    "owner": "bb",
                    "pid": os.getpid(),
                    "sock": "/tmp/x.sock",
                    "expiresAtMs": now + 600_000,
                },
                f,
            )
        # live lease, DEAD pid: reaped only under force_dead
        with open(os.path.join(d, "cc.json"), "w") as f:
            json.dump(
                {
                    "owner": "cc",
                    "pid": 2**22 + 12345,
                    "sock": "/tmp/y.sock",
                    "expiresAtMs": now + 600_000,
                },
                f,
            )
        reaped, leftovers = fleet_router.reap_members(d)
        assert reaped == 1 and leftovers == []
        assert not os.path.exists(sock)
        assert set(fleet_router.read_members(d)) == {"bb", "cc"}
        reaped, leftovers = fleet_router.reap_members(d, force_dead=True)
        assert reaped == 1 and leftovers == []
        assert set(fleet_router.read_members(d)) == {"bb"}

    def test_rendezvous_is_stable_and_balanced(self):
        from hyperspace_tpu.serve.router import rendezvous_owner

        owners = ["m1", "m2", "m3"]
        digests = [f"{i:040x}" for i in range(600)]
        first = [rendezvous_owner(owners, d) for d in digests]
        assert first == [rendezvous_owner(owners, d) for d in digests]
        counts = {o: first.count(o) for o in owners}
        assert all(c > 100 for c in counts.values()), counts
        # removing a member only moves ITS digests
        moved = sum(
            1
            for d, was in zip(digests, first)
            if was != "m3" and rendezvous_owner(["m1", "m2"], d) != was
        )
        assert moved == 0

    def test_spool_sweep_reaps_orphans_and_counts(self, fleet_env):
        s = fleet_env["make_session"](**{C.FLEET_FAST_ENABLED: False})
        s.conf.set(C.FLEET_SINGLEFLIGHT_CLAIM_MS, 100)
        fe = s.serve_frontend
        try:
            sd = spool_dir(s.conf)
            os.makedirs(sd, exist_ok=True)
            old = time.time() - 60.0
            for name in (
                "deadbeef.arrow.trace",  # orphan sidecar (no .arrow)
                "deadbeef.claim",  # stale claim
                ".tmp_spool_zz",  # crash-leaked publish temp
            ):
                p = os.path.join(sd, name)
                with open(p, "w") as f:
                    f.write("x")
                os.utime(p, (old, old))
            src = fleet_env["src"]
            q = s.read.parquet(src)
            q = q.filter(q["k"] == 21)
            fe.serve(q)  # the winner's publish runs the sweep
            names = os.listdir(sd)
            assert "deadbeef.arrow.trace" not in names
            assert "deadbeef.claim" not in names
            assert ".tmp_spool_zz" not in names
            st = fe.stats()["fleet"]
            assert st["spool_reaped_traces"] == 1, st
            assert st["spool_reaped_claims"] == 1, st
            assert st["spool_reaped_tmp"] == 1, st
        finally:
            fe.close()

    def test_fleet_wide_slo_sheds_on_gossiped_depth(self, fleet_env):
        conf = {
            C.FLEET_CLASS_KEY_PREFIX + "batch.maxConcurrency": 1,
            C.FLEET_CLASS_KEY_PREFIX + "batch.maxQueueDepth": 2,
            C.SERVE_MAX_CONCURRENCY: 8,
        }
        s1 = fleet_env["make_session"](**conf)
        s2 = fleet_env["make_session"](**conf)
        fe1, fe2 = s1.serve_frontend, s2.serve_frontend
        try:
            gate = threading.Event()
            fe2._execute_pinned = lambda plan, pin: (
                gate.wait(10.0),
                pa.table({"x": pa.array([1])}),
            )[1]
            src = fleet_env["src"]

            def q(sess, i):
                df = sess.read.parquet(src)
                return df.filter(df["k"] == i)

            # saturate fe2's batch tier (1 running + 1 pending = depth 2)
            futs = [fe2.submit(q(s2, i), slo_class="batch") for i in (0, 1)]
            # wait for fe1 to have RECEIVED the depth-2 gossip (a
            # depth-0 gossip from before the submits does not count)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                fe2._router.push_gossip_now()
                with fe1._lock:
                    depth = sum(
                        c.get("batch", 0) for _ts, c in fe1._peer_slo.values()
                    )
                if depth >= 2:
                    break
                time.sleep(0.01)
            assert depth >= 2
            # fe1 is idle — but the FLEET's batch tier is at its bound,
            # so admission sheds here too (batch before interactive)
            with pytest.raises(ServeOverloadedError, match="fleet"):
                fe1.submit(q(s1, 50), slo_class="batch")
            t = fe1.serve(q(s1, 51), slo_class="interactive")
            assert t.num_rows >= 0
            gate.set()
            for f in futs:
                f.result(timeout=10)
        finally:
            gate.set()
            fe1.close()
            fe2.close()


# ---------------------------------------------------------------------------
# Per-tenant SLO classes
# ---------------------------------------------------------------------------


class TestSloClasses:
    def _frontend(self, fleet_env, **conf):
        s = fleet_env["make_session"](**{C.FLEET_ENABLED: False, **conf})
        return s, ServeFrontend(s)

    def test_class_max_concurrency_gates_running(self, fleet_env):
        s, fe = self._frontend(
            fleet_env,
            **{
                C.FLEET_CLASS_KEY_PREFIX + "batch.maxConcurrency": 1,
                C.SERVE_MAX_CONCURRENCY: 8,
            },
        )
        try:
            gate = threading.Event()
            running = []

            def slow_exec(plan, pin):
                running.append(1)
                assert gate.wait(10.0)
                return pa.table({"x": pa.array([len(running)])})

            fe._execute_pinned = slow_exec
            src = fleet_env["src"]
            futs = []
            for i in range(4):
                q = s.read.parquet(src)
                q = q.filter(q["k"] == i)  # distinct plans: no dedup
                futs.append(fe.submit(q, slo_class="batch"))
            time.sleep(0.2)
            st = fe.stats()["slo_classes"]["batch"]
            assert st["running"] == 1, st
            assert st["pending"] == 3, st
            assert len(running) == 1
            gate.set()
            for f in futs:
                f.result(timeout=10)
            st = fe.stats()["slo_classes"]["batch"]
            assert st["running"] == 0 and st["pending"] == 0
            assert st["admitted"] == 4
        finally:
            fe.close()

    def test_batch_sheds_before_interactive(self, fleet_env):
        s, fe = self._frontend(
            fleet_env,
            **{
                C.FLEET_CLASS_KEY_PREFIX + "batch.maxConcurrency": 1,
                C.FLEET_CLASS_KEY_PREFIX + "batch.maxQueueDepth": 2,
                C.SERVE_MAX_CONCURRENCY: 8,
                C.SERVE_MAX_QUEUE_DEPTH: 64,
            },
        )
        try:
            gate = threading.Event()
            fe._execute_pinned = lambda plan, pin: (
                gate.wait(10.0),
                pa.table({"x": pa.array([1])}),
            )[1]
            src = fleet_env["src"]

            def q(i):
                df = s.read.parquet(src)
                return df.filter(df["k"] == i)

            futs = [fe.submit(q(i), slo_class="batch") for i in range(2)]
            # the batch tier is at its depth: the third submit sheds...
            with pytest.raises(ServeOverloadedError, match="batch"):
                fe.submit(q(99), slo_class="batch")
            # ...while the interactive tier (and unclassed traffic) is
            # untouched by batch pressure
            f_int = fe.submit(q(7), slo_class="interactive")
            f_un = fe.submit(q(8))
            gate.set()
            for f in futs + [f_int, f_un]:
                f.result(timeout=10)
            st = fe.stats()
            assert st["slo_classes"]["batch"]["shed"] == 1
            assert st["shed"] == 1
        finally:
            fe.close()

    def test_unconfigured_class_unlimited(self, fleet_env):
        s, fe = self._frontend(fleet_env)
        try:
            src = fleet_env["src"]
            q = s.read.parquet(src)
            q = q.filter(q["k"] == 3)
            t = fe.serve(q, slo_class="nosuch")
            assert t.num_rows >= 0
            assert "slo_classes" not in fe.stats()
        finally:
            fe.close()

    def test_close_fails_parked_admissions(self, fleet_env):
        s, fe = self._frontend(
            fleet_env,
            **{C.FLEET_CLASS_KEY_PREFIX + "batch.maxConcurrency": 1},
        )
        gate = threading.Event()
        fe._execute_pinned = lambda plan, pin: (
            gate.wait(10.0),
            pa.table({"x": pa.array([1])}),
        )[1]
        src = fleet_env["src"]

        def q(i):
            df = s.read.parquet(src)
            return df.filter(df["k"] == i)

        f0 = fe.submit(q(0), slo_class="batch")
        f1 = fe.submit(q(1), slo_class="batch")  # parked
        gate.set()
        f0.result(timeout=10)
        fe.close(wait=False)
        # the parked admission either dispatched before close (ran) or
        # was failed with a typed error — never silently dropped
        try:
            f1.result(timeout=10)
        except Exception as exc:
            assert "closed" in str(exc).lower()


# ---------------------------------------------------------------------------
# Session integration
# ---------------------------------------------------------------------------


class TestSessionIntegration:
    def test_frontend_type_follows_fleet_flag(self, fleet_env):
        s = fleet_env["make_session"]()
        fe = s.serve_frontend
        assert isinstance(fe, FleetFrontend)
        s.conf.set(C.FLEET_ENABLED, False)
        fe2 = s.serve_frontend
        assert type(fe2) is ServeFrontend
        assert fe.closed  # the mode-mismatched frontend was retired
        s.conf.set(C.FLEET_ENABLED, True)
        fe3 = s.serve_frontend
        assert isinstance(fe3, FleetFrontend)
        fe3.close()


# ---------------------------------------------------------------------------
# The real thing: N OS processes over one lake (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestFleetProcesses:
    def test_two_processes_single_flight_and_convergence(self, tmp_path):
        from hyperspace_tpu.testing import fleet_harness

        rep = fleet_harness.run_fleet(
            str(tmp_path / "fleet"),
            n_procs=2,
            iters=3,
            rows=8000,
            fastpath_phase=True,
        )
        assert rep["wrong_answers"] == 0
        # cross-process dedup now lands on the fast plane first (owner
        # handoffs / result-cache hits); the spool remains the fallback
        dedup = (
            rep["cross_process_dedup"]
            + rep["fast_handoffs"]
            + rep["fast_result_hits"]
        )
        assert dedup > 0, rep
        assert rep["fast_frontends"] == 2, rep
        assert rep["fast_push_received"] >= 1, rep  # pushed fanout seen
        assert rep["fast_handoffs"] >= 1, rep  # spool-free handoff seen
        assert rep["probe_mismatches"] == 0, rep
        assert rep["leaked_pin_files"] == 0
        assert rep["leaked_fast_members"] == 0

    def test_kill_nine_mid_serve(self, tmp_path):
        from hyperspace_tpu.testing import fleet_harness

        rep = fleet_harness.run_fleet(
            str(tmp_path / "chaos"),
            n_procs=3,
            iters=3,
            rows=8000,
            kill_one=True,
            fastpath_phase=True,
        )
        assert rep["killed"] and rep["workers_reporting"] == 2
        assert rep["wrong_answers"] == 0
        # the dead owner's member file outlives it (generous harness
        # lease): survivor probes MUST degrade fast->durable, answer
        # bit-identically, and the convergence reap must leave no member
        # file or socket behind
        assert rep["fast_fallbacks"] >= 1, rep
        assert rep["probe_mismatches"] == 0, rep
        assert rep["leaked_pin_files"] == 0
        assert rep["leaked_fast_members"] == 0
