"""Replicated serve fleet (docs/fleet-serve.md): durable pins, version
fanout, cross-process single-flight, per-tenant SLO classes.

The durable-pin × GC/vacuum interaction lives in
``tests/test_crash_recovery.py`` (``TestCrossProcessPins``); this file
covers the serve-tier planes — the bus, the claim/spool single-flight
(driven through two in-process ``FleetFrontend`` instances, which share
NO in-process state by construction, so the file protocol is what
coordinates them), the SLO-class scheduler, and (slow) the real
multi-process harness with its kill -9 rung.
"""

import json
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import functions as F
from hyperspace_tpu.exceptions import ServeOverloadedError
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.serve.bus import FleetBus
from hyperspace_tpu.serve.fleet import FleetFrontend, spool_dir
from hyperspace_tpu.serve.frontend import ServeFrontend


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


@pytest.fixture
def fleet_env(tmp_path):
    """One lake + two fleet sessions over it (the in-process stand-in
    for two frontend processes: separate sessions, separate caches,
    coordination only through the lake's files)."""
    from hyperspace_tpu.session import HyperspaceSession

    src = tmp_path / "src"
    src.mkdir()
    rng = np.random.default_rng(7)
    n = 4000
    pq.write_table(
        pa.table(
            {
                "k": pa.array(rng.integers(0, 60, n), pa.int64()),
                "v": pa.array(rng.integers(-500, 500, n), pa.int64()),
            }
        ),
        str(src / "part-0.parquet"),
    )
    index_root = str(tmp_path / "indexes")

    def make_session(**conf):
        s = HyperspaceSession()
        s.conf.set(C.INDEX_SYSTEM_PATH, index_root)
        s.conf.set(C.INDEX_NUM_BUCKETS, 4)
        s.conf.set(C.FLEET_ENABLED, True)
        s.conf.set(C.SERVE_CACHE_ENABLED, True)
        s.conf.set(C.FLEET_BUS_POLL_MS, 20)
        for k, v in conf.items():
            s.conf.set(k, v)
        s.enable_hyperspace()
        return s

    s1 = make_session()
    hs1 = Hyperspace(s1)
    df = s1.read.parquet(str(src))
    hs1.create_index(df, CoveringIndexConfig("fidx", ["k"], ["v"]))
    return {
        "src": str(src),
        "index_root": index_root,
        "make_session": make_session,
        "s1": s1,
        "hs1": hs1,
        "rng": rng,
    }


# ---------------------------------------------------------------------------
# The fanout bus
# ---------------------------------------------------------------------------


class TestFleetBus:
    def test_publish_poll_roundtrip(self, tmp_path):
        d = str(tmp_path / "bus")
        a = FleetBus(d, retain_ms=60_000)
        b = FleetBus(d, retain_ms=60_000)
        b.prime()
        a.publish({"type": "index_changed", "root": "/x"})
        a.publish({"type": "index_changed", "root": "/y"})
        events = b.poll_once()
        assert [e["root"] for e in events] == ["/x", "/y"]
        assert b.poll_once() == []  # seen once
        assert b.received == 2

    def test_own_events_skipped(self, tmp_path):
        d = str(tmp_path / "bus")
        a = FleetBus(d)
        a.prime()
        a.publish({"type": "index_changed", "root": "/x"})
        assert a.poll_once() == []

    def test_prime_skips_history(self, tmp_path):
        d = str(tmp_path / "bus")
        a = FleetBus(d)
        a.publish({"type": "index_changed", "root": "/old"})
        b = FleetBus(d)
        b.prime()
        assert b.poll_once() == []
        a.publish({"type": "index_changed", "root": "/new"})
        assert [e["root"] for e in b.poll_once()] == ["/new"]

    def test_retention_prune(self, tmp_path):
        d = str(tmp_path / "bus")
        a = FleetBus(d, retain_ms=80)
        a.publish({"type": "index_changed", "root": "/x"})
        time.sleep(0.15)
        a.publish({"type": "index_changed", "root": "/y"})
        assert a.pruned >= 1
        names = [f for f in os.listdir(d) if f.endswith(".json")]
        assert len(names) == 1

    def test_torn_event_skipped(self, tmp_path):
        d = str(tmp_path / "bus")
        os.makedirs(d)
        b = FleetBus(d)
        b.prime()
        with open(os.path.join(d, "9999999999999.dead.000001.json"), "w") as f:
            f.write('{"type": "ind')
        assert b.poll_once() == []

    def test_subscriber_thread_delivers(self, tmp_path):
        d = str(tmp_path / "bus")
        got = []
        done = threading.Event()
        b = FleetBus(d, poll_ms=10)
        b.start(lambda e: (got.append(e), done.set()))
        try:
            FleetBus(d).publish({"type": "index_changed", "root": "/z"})
            assert done.wait(5.0)
            assert got[0]["root"] == "/z"
        finally:
            b.stop()


# ---------------------------------------------------------------------------
# ServeCache fanout eviction
# ---------------------------------------------------------------------------


class TestEvictPathsUnder:
    def test_evicts_only_matching_index(self):
        from hyperspace_tpu.execution.serve_cache import ServeCache

        c = ServeCache(1 << 20)
        fp_a = (("/lake/idxA/v__=1/part-0.parquet", 10, 1),)
        fp_b = (("/lake/idxB/v__=1/part-0.parquet", 10, 1),)
        c.put(("scan", fp_a), "a", 10)
        c.put(("zonemap", fp_a), "za", 10)
        c.put(("joinside", (fp_a, fp_b), ("k",), ("k",)), "j", 10)
        c.put(("scan", fp_b), "b", 10)
        assert c.evict_paths_under("/lake/idxA") == 3
        assert c.get(("scan", fp_b)) == "b"
        assert c.get(("scan", fp_a)) is None
        assert c.resident_bytes == 10


# ---------------------------------------------------------------------------
# Aggstate push payloads (ROADMAP 2c)
# ---------------------------------------------------------------------------


class TestAggstatePush:
    def test_payload_roundtrip(self, fleet_env):
        from hyperspace_tpu.execution.serve_cache import ServeCache
        from hyperspace_tpu.indexes import aggindex

        s1 = fleet_env["s1"]
        entries = s1.index_manager.get_indexes([C.States.ACTIVE])
        files = entries[0].content.files
        payload = aggindex.fanout_payload(files)
        assert payload is not None
        # JSON round trip, as the bus would carry it
        payload = json.loads(json.dumps(payload))
        cache = ServeCache(1 << 24)
        aggindex.invalidate_local_cache()
        assert aggindex.install_fanout_payload(payload, cache)
        assert cache.bytes_by_kind().get("aggstate", 0) > 0

    def test_stale_payload_dropped(self, fleet_env):
        from hyperspace_tpu.indexes import aggindex

        s1 = fleet_env["s1"]
        entries = s1.index_manager.get_indexes([C.States.ACTIVE])
        payload = aggindex.fanout_payload(entries[0].content.files)
        payload["fp"][0][1] += 1  # stats moved on: stale push
        assert not aggindex.install_fanout_payload(payload, None)

    def test_refresh_fans_out_to_peer(self, fleet_env):
        src, rng = fleet_env["src"], fleet_env["rng"]
        s2 = fleet_env["make_session"]()
        fe2 = s2.serve_frontend
        try:
            assert isinstance(fe2, FleetFrontend)
            pq.write_table(
                pa.table(
                    {
                        "k": pa.array(rng.integers(0, 60, 500), pa.int64()),
                        "v": pa.array(
                            rng.integers(-500, 500, 500), pa.int64()
                        ),
                    }
                ),
                os.path.join(src, "part-1.parquet"),
            )
            fleet_env["hs1"].refresh_index("fidx", "incremental")
            # wait on bus_installed, not bus_events: the callback counts
            # the event BEFORE it installs the payload
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                st = fe2.stats()["fleet"]
                if st["bus_installed"] >= 1:
                    break
                time.sleep(0.02)
            st = fe2.stats()["fleet"]
            assert st["bus_events"] >= 1, st
            assert st["bus_installed"] >= 1, st
            # the peer serves the NEW snapshot correctly
            df = s2.read.parquet(src)
            q = df.filter(df["k"] >= 10).agg(F.count().alias("n"))
            got = fe2.serve(q)
            s2.disable_hyperspace()
            want = q.collect()
            s2.enable_hyperspace()
            assert got.equals(want)
        finally:
            fe2.close()


# ---------------------------------------------------------------------------
# Cross-process single-flight (claim + spool)
# ---------------------------------------------------------------------------


class TestSingleFlight:
    def test_two_frontends_one_execution(self, fleet_env):
        s1 = fleet_env["s1"]
        s2 = fleet_env["make_session"]()
        fe1, fe2 = s1.serve_frontend, s2.serve_frontend
        try:
            src = fleet_env["src"]
            q1 = s1.read.parquet(src)
            q1 = q1.filter(q1["k"] == 11)
            q2 = s2.read.parquet(src)
            q2 = q2.filter(q2["k"] == 11)
            t1 = fe1.serve(q1)
            t2 = fe2.serve(q2)
            assert sorted_table(t1).equals(sorted_table(t2))
            st1, st2 = fe1.stats()["fleet"], fe2.stats()["fleet"]
            assert st1["claims_won"] + st2["claims_won"] == 1
            assert st1["spool_hits"] + st2["spool_hits"] == 1
            # the answer is correct vs the unindexed truth
            s1.disable_hyperspace()
            want = q1.collect()
            s1.enable_hyperspace()
            assert sorted_table(t1).equals(sorted_table(want))
        finally:
            fe1.close()
            fe2.close()

    def test_expired_claim_taken_over(self, fleet_env):
        s2 = fleet_env["make_session"]()
        s2.conf.set(C.FLEET_SINGLEFLIGHT_CLAIM_MS, 30)
        fe2 = s2.serve_frontend
        try:
            # a dead winner's claim (kill -9 mid-serve) sits in the
            # spool; its lease expires and fe2 takes the claim over
            claim = os.path.join(spool_dir(s2.conf), "deadbeef.claim")
            os.makedirs(os.path.dirname(claim), exist_ok=True)
            with open(claim, "w") as f:
                json.dump({"owner": "dead", "expiresAtMs": 1}, f)
            assert fe2._try_claim(claim) == "won"
            # a LIVE claim is respected
            claim2 = os.path.join(spool_dir(s2.conf), "cafebabe.claim")
            with open(claim2, "w") as f:
                json.dump(
                    {
                        "owner": "live",
                        "expiresAtMs": int(time.time() * 1000) + 600_000,
                    },
                    f,
                )
            assert fe2._try_claim(claim2) == "held"
        finally:
            fe2.close()

    def test_wait_timeout_executes_locally(self, fleet_env):
        s2 = fleet_env["make_session"]()
        s2.conf.set(C.FLEET_SINGLEFLIGHT_WAIT_MS, 50)
        s2.conf.set(C.FLEET_SINGLEFLIGHT_CLAIM_MS, 600_000)
        fe2 = s2.serve_frontend
        try:
            src = fleet_env["src"]
            q = s2.read.parquet(src)
            q = q.filter(q["k"] == 31)
            pin = fe2._pin()
            digest = fe2._plan_digest(q.logical_plan, pin)
            claim = os.path.join(spool_dir(s2.conf), digest + ".claim")
            os.makedirs(os.path.dirname(claim), exist_ok=True)
            with open(claim, "w") as f:
                json.dump(
                    {
                        "owner": "live-elsewhere",
                        "expiresAtMs": int(time.time() * 1000) + 600_000,
                    },
                    f,
                )
            t = fe2.serve(q)  # waits 50ms, then serves locally
            s2.disable_hyperspace()
            want = q.collect()
            s2.enable_hyperspace()
            assert sorted_table(t).equals(sorted_table(want))
            st = fe2.stats()["fleet"]
            assert st["singleflight_local"] >= 1, st
            assert st["claim_waits"] >= 1, st
        finally:
            fe2.close()

    def test_spool_prune_respects_budget(self, fleet_env):
        s2 = fleet_env["make_session"]()
        s2.conf.set(C.FLEET_SPOOL_MAX_BYTES, 1)
        fe2 = s2.serve_frontend
        try:
            src = fleet_env["src"]
            q = s2.read.parquet(src)
            q = q.filter(q["k"] == 42)
            fe2.serve(q)
            sd = spool_dir(s2.conf)
            arrows = [f for f in os.listdir(sd) if f.endswith(".arrow")]
            assert arrows == []  # over-budget results pruned immediately
        finally:
            fe2.close()


# ---------------------------------------------------------------------------
# Per-tenant SLO classes
# ---------------------------------------------------------------------------


class TestSloClasses:
    def _frontend(self, fleet_env, **conf):
        s = fleet_env["make_session"](**{C.FLEET_ENABLED: False, **conf})
        return s, ServeFrontend(s)

    def test_class_max_concurrency_gates_running(self, fleet_env):
        s, fe = self._frontend(
            fleet_env,
            **{
                C.FLEET_CLASS_KEY_PREFIX + "batch.maxConcurrency": 1,
                C.SERVE_MAX_CONCURRENCY: 8,
            },
        )
        try:
            gate = threading.Event()
            running = []

            def slow_exec(plan, pin):
                running.append(1)
                assert gate.wait(10.0)
                return pa.table({"x": pa.array([len(running)])})

            fe._execute_pinned = slow_exec
            src = fleet_env["src"]
            futs = []
            for i in range(4):
                q = s.read.parquet(src)
                q = q.filter(q["k"] == i)  # distinct plans: no dedup
                futs.append(fe.submit(q, slo_class="batch"))
            time.sleep(0.2)
            st = fe.stats()["slo_classes"]["batch"]
            assert st["running"] == 1, st
            assert st["pending"] == 3, st
            assert len(running) == 1
            gate.set()
            for f in futs:
                f.result(timeout=10)
            st = fe.stats()["slo_classes"]["batch"]
            assert st["running"] == 0 and st["pending"] == 0
            assert st["admitted"] == 4
        finally:
            fe.close()

    def test_batch_sheds_before_interactive(self, fleet_env):
        s, fe = self._frontend(
            fleet_env,
            **{
                C.FLEET_CLASS_KEY_PREFIX + "batch.maxConcurrency": 1,
                C.FLEET_CLASS_KEY_PREFIX + "batch.maxQueueDepth": 2,
                C.SERVE_MAX_CONCURRENCY: 8,
                C.SERVE_MAX_QUEUE_DEPTH: 64,
            },
        )
        try:
            gate = threading.Event()
            fe._execute_pinned = lambda plan, pin: (
                gate.wait(10.0),
                pa.table({"x": pa.array([1])}),
            )[1]
            src = fleet_env["src"]

            def q(i):
                df = s.read.parquet(src)
                return df.filter(df["k"] == i)

            futs = [fe.submit(q(i), slo_class="batch") for i in range(2)]
            # the batch tier is at its depth: the third submit sheds...
            with pytest.raises(ServeOverloadedError, match="batch"):
                fe.submit(q(99), slo_class="batch")
            # ...while the interactive tier (and unclassed traffic) is
            # untouched by batch pressure
            f_int = fe.submit(q(7), slo_class="interactive")
            f_un = fe.submit(q(8))
            gate.set()
            for f in futs + [f_int, f_un]:
                f.result(timeout=10)
            st = fe.stats()
            assert st["slo_classes"]["batch"]["shed"] == 1
            assert st["shed"] == 1
        finally:
            fe.close()

    def test_unconfigured_class_unlimited(self, fleet_env):
        s, fe = self._frontend(fleet_env)
        try:
            src = fleet_env["src"]
            q = s.read.parquet(src)
            q = q.filter(q["k"] == 3)
            t = fe.serve(q, slo_class="nosuch")
            assert t.num_rows >= 0
            assert "slo_classes" not in fe.stats()
        finally:
            fe.close()

    def test_close_fails_parked_admissions(self, fleet_env):
        s, fe = self._frontend(
            fleet_env,
            **{C.FLEET_CLASS_KEY_PREFIX + "batch.maxConcurrency": 1},
        )
        gate = threading.Event()
        fe._execute_pinned = lambda plan, pin: (
            gate.wait(10.0),
            pa.table({"x": pa.array([1])}),
        )[1]
        src = fleet_env["src"]

        def q(i):
            df = s.read.parquet(src)
            return df.filter(df["k"] == i)

        f0 = fe.submit(q(0), slo_class="batch")
        f1 = fe.submit(q(1), slo_class="batch")  # parked
        gate.set()
        f0.result(timeout=10)
        fe.close(wait=False)
        # the parked admission either dispatched before close (ran) or
        # was failed with a typed error — never silently dropped
        try:
            f1.result(timeout=10)
        except Exception as exc:
            assert "closed" in str(exc).lower()


# ---------------------------------------------------------------------------
# Session integration
# ---------------------------------------------------------------------------


class TestSessionIntegration:
    def test_frontend_type_follows_fleet_flag(self, fleet_env):
        s = fleet_env["make_session"]()
        fe = s.serve_frontend
        assert isinstance(fe, FleetFrontend)
        s.conf.set(C.FLEET_ENABLED, False)
        fe2 = s.serve_frontend
        assert type(fe2) is ServeFrontend
        assert fe.closed  # the mode-mismatched frontend was retired
        s.conf.set(C.FLEET_ENABLED, True)
        fe3 = s.serve_frontend
        assert isinstance(fe3, FleetFrontend)
        fe3.close()


# ---------------------------------------------------------------------------
# The real thing: N OS processes over one lake (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestFleetProcesses:
    def test_two_processes_single_flight_and_convergence(self, tmp_path):
        from hyperspace_tpu.testing import fleet_harness

        rep = fleet_harness.run_fleet(
            str(tmp_path / "fleet"), n_procs=2, iters=3, rows=8000
        )
        assert rep["wrong_answers"] == 0
        assert rep["cross_process_dedup"] > 0
        assert rep["leaked_pin_files"] == 0

    def test_kill_nine_mid_serve(self, tmp_path):
        from hyperspace_tpu.testing import fleet_harness

        rep = fleet_harness.run_fleet(
            str(tmp_path / "chaos"),
            n_procs=3,
            iters=3,
            rows=8000,
            kill_one=True,
        )
        assert rep["killed"] and rep["workers_reporting"] == 2
        assert rep["wrong_answers"] == 0
        assert rep["leaked_pin_files"] == 0
