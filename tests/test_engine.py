"""Query engine tests: expressions, executor, joins.

Differential style (the reference's `checkAnswer` pattern,
``E2EHyperspaceRulesTest.scala:76-120``): engine results are compared
against independint pyarrow/python evaluation of the same query.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.io.columnar import ColumnarBatch
from hyperspace_tpu.plan import expressions as E


@pytest.fixture
def batch():
    return ColumnarBatch.from_arrow(
        pa.table(
            {
                "k": pa.array([1, 2, None, 4, 5], type=pa.int64()),
                "v": pa.array([10.0, 20.0, 30.0, None, 50.0]),
                "s": pa.array(["b", "a", "c", None, "b"]),
            }
        )
    )


def rows(mask):
    return np.nonzero(mask)[0].tolist()


class TestExpressions:
    def test_numeric_comparisons(self, batch):
        c = E.Col("k")
        assert rows(E.filter_mask(c > 1, batch)) == [1, 3, 4]
        assert rows(E.filter_mask(c == 4, batch)) == [3]
        assert rows(E.filter_mask(c <= 2, batch)) == [0, 1]
        assert rows(E.filter_mask(c != 2, batch)) == [0, 3, 4]

    def test_null_semantics(self, batch):
        k, v = E.Col("k"), E.Col("v")
        # NULL rows never pass comparisons, even negated ones
        assert rows(E.filter_mask(~(k > 1), batch)) == [0]
        assert rows(E.filter_mask(E.IsNull(k), batch)) == [2]
        assert rows(E.filter_mask(k.is_not_null(), batch)) == [0, 1, 3, 4]
        # Kleene OR: (k>1) OR (v>0) — row 2 has k null but v=30>0 ⇒ true
        assert rows(E.filter_mask((k > 1) | (v > 0.0), batch)) == [0, 1, 2, 3, 4]
        # Kleene AND: row 3 v null ⇒ unknown
        assert rows(E.filter_mask((k > 1) & (v > 0.0), batch)) == [1, 4]

    def test_string_comparisons(self, batch):
        s = E.Col("s")
        assert rows(E.filter_mask(s == "b", batch)) == [0, 4]
        assert rows(E.filter_mask(s != "b", batch)) == [1, 2]
        assert rows(E.filter_mask(s < "b", batch)) == [1]
        assert rows(E.filter_mask(s >= "b", batch)) == [0, 2, 4]
        # literal absent from dictionary
        assert rows(E.filter_mask(s == "zz", batch)) == []
        assert rows(E.filter_mask(s <= "aa", batch)) == [1]

    def test_in(self, batch):
        assert rows(E.filter_mask(E.Col("k").isin(1, 5, 99), batch)) == [0, 4]
        assert rows(E.filter_mask(E.Col("s").isin("a", "c", "zz"), batch)) == [1, 2]

    def test_references_and_conjuncts(self):
        e = (E.Col("a") > 1) & (E.Col("b") == E.Col("c"))
        assert E.references(e) == {"a", "b", "c"}
        assert len(E.split_conjuncts(e)) == 2
        assert E.equi_join_pairs(E.Col("x") == E.Col("y")) == [("x", "y")]
        assert E.equi_join_pairs(E.Col("x") > E.Col("y")) is None

    def test_expr_bool_raises(self):
        with pytest.raises(TypeError):
            bool(E.Col("a") == E.Col("b"))


class TestDeviceFilter:
    """Device kernel must agree with the host evaluator on every case."""

    EXPRS = [
        lambda: E.Col("k") > 1,
        lambda: E.Col("k") == 4,
        lambda: ~(E.Col("k") > 1),
        lambda: (E.Col("k") > 1) | (E.Col("v") > 0.0),
        lambda: (E.Col("k") > 1) & (E.Col("v") > 0.0),
        lambda: E.Col("s") == "b",
        lambda: E.Col("s") < "b",
        lambda: E.Col("s") >= "b",
        lambda: E.Col("s") == "zz",
        lambda: E.Col("k").isin(1, 5, 99),
        lambda: E.Col("s").isin("a", "c", "zz"),
        lambda: E.IsNull(E.Col("k")),
        lambda: E.Col("k").is_not_null() & (E.Col("s") != "b"),
        lambda: E.Col("k") == E.Col("k"),
    ]

    @pytest.mark.parametrize("mk", EXPRS)
    def test_device_matches_host(self, batch, mk):
        from hyperspace_tpu.ops.filter import device_filter_mask

        e = mk()
        np.testing.assert_array_equal(
            device_filter_mask(e, batch), E.filter_mask(e, batch)
        )


@pytest.fixture
def two_tables(tmp_path, session):
    rng = np.random.default_rng(7)
    n1, n2 = 500, 300
    orders = pa.table(
        {
            "o_key": pa.array(rng.integers(0, 100, n1), type=pa.int64()),
            "o_val": pa.array(rng.normal(size=n1)),
            "o_tag": pa.array([f"t{int(x)%5}" for x in rng.integers(0, 100, n1)]),
        }
    )
    items = pa.table(
        {
            "l_key": pa.array(rng.integers(0, 100, n2), type=pa.int64()),
            "l_qty": pa.array(rng.integers(1, 50, n2), type=pa.int64()),
        }
    )
    d1, d2 = tmp_path / "orders", tmp_path / "items"
    d1.mkdir(), d2.mkdir()
    pq.write_table(orders, d1 / "part-0.parquet")
    pq.write_table(items, d2 / "part-0.parquet")
    return str(d1), str(d2), orders, items


class TestExecutor:
    def test_scan_collect(self, session, sample_parquet):
        df = session.read.parquet(sample_parquet)
        out = df.collect()
        assert out.num_rows == 300
        assert set(df.columns) == {"date", "rguid", "clicks", "query", "imprs"}

    def test_filter_project_differential(self, session, sample_parquet):
        import pyarrow.compute as pc

        df = session.read.parquet(sample_parquet)
        got = (
            df.filter((df["clicks"] > 500) & (df["query"] == "banana"))
            .select("clicks", "imprs")
            .collect()
        )
        raw = df.collect()
        want = raw.filter(
            pc.and_(
                pc.greater(raw.column("clicks"), 500),
                pc.equal(raw.column("query"), "banana"),
            )
        ).select(["clicks", "imprs"])
        assert got.sort_by("clicks").equals(want.sort_by("clicks"))
        assert got.num_rows > 0

    def test_join_differential(self, session, two_tables):
        d1, d2, orders, items = two_tables
        dfo = session.read.parquet(d1)
        dfi = session.read.parquet(d2)
        got = (
            dfo.join(dfi, on=dfo["o_key"] == dfi["l_key"])
            .select("o_key", "l_qty")
            .collect()
        )
        # independent check via python dict join
        import collections

        right = collections.defaultdict(list)
        for k, q in zip(
            items.column("l_key").to_pylist(), items.column("l_qty").to_pylist()
        ):
            right[k].append(q)
        want = []
        for k in orders.column("o_key").to_pylist():
            for q in right.get(k, []):
                want.append((k, q))
        got_pairs = sorted(
            zip(got.column("o_key").to_pylist(), got.column("l_qty").to_pylist())
        )
        assert got_pairs == sorted(want)
        assert len(got_pairs) > 0

    def test_string_filter_differential(self, session, two_tables):
        import pyarrow.compute as pc

        d1, _d2, orders, _items = two_tables
        dfo = session.read.parquet(d1)
        got = dfo.filter(dfo["o_tag"] == "t3").count()
        want = orders.filter(pc.equal(orders.column("o_tag"), "t3")).num_rows
        assert got == want

    def test_string_key_join(self, session, tmp_path):
        a = pa.table({"tag_a": ["x", "y", "z", "x"], "va": [1, 2, 3, 4]})
        b = pa.table({"tag_b": ["x", "x", "q"], "vb": [10, 20, 30]})
        (tmp_path / "a").mkdir(), (tmp_path / "b").mkdir()
        pq.write_table(a, tmp_path / "a" / "p.parquet")
        pq.write_table(b, tmp_path / "b" / "p.parquet")
        dfa = session.read.parquet(str(tmp_path / "a"))
        dfb = session.read.parquet(str(tmp_path / "b"))
        got = dfa.join(dfb, on=dfa["tag_a"] == dfb["tag_b"]).collect()
        pairs = sorted(
            zip(got.column("va").to_pylist(), got.column("vb").to_pylist())
        )
        assert pairs == [(1, 10), (1, 20), (4, 10), (4, 20)]

    def test_csv_scan(self, session, tmp_path):
        p = tmp_path / "c"
        p.mkdir()
        (p / "a.csv").write_text("x,y\n1,a\n2,b\n3,a\n")
        df = session.read.csv(str(p))
        assert df.filter(df["y"] == "a").count() == 2

    def test_empty_result(self, session, sample_parquet):
        df = session.read.parquet(sample_parquet)
        out = df.filter(df["clicks"] > 10**9).select("clicks").collect()
        assert out.num_rows == 0
