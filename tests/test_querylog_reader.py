"""Query-log READER hardening (obs/querylog.py, docs/advisor.md).

The advisor mines logs written by a fleet of processes that crash,
rotate and upgrade independently — so the reader contract is: union
everything readable, skip everything else, raise never. Three legs:

* torn trailing lines (a writer died mid-append) are skipped while
  every complete line before AND after the tear still reads;
* the unsealed active file of a writer that crashed mid-rotation
  (``mid_querylog_rotate``) is picked up by the union alongside other
  processes' segments;
* records with an unknown/newer ``schema_v`` are dropped by
  ``read_valid_records`` with a counter increment — a half-upgraded
  fleet's mixed log profiles cleanly on the old binary.
"""

import json
import os

import pytest

from hyperspace_tpu.obs import metrics, querylog
from hyperspace_tpu.testing import faults
from hyperspace_tpu.testing.faults import SimulatedCrash


def _rec(i, **over):
    rec = {
        "schema_v": querylog.SCHEMA_V,
        "ts_ms": 1000 + i,
        "fingerprint": f"fp{i}",
        "duration_s": 0.01,
        "status": "ok",
        "stages": {"scan": 0.001},
        "rows_returned": i,
    }
    rec.update(over)
    return rec


def _write_segment(path, records, tail=""):
    with open(path, "w", encoding="utf-8") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")
        fh.write(tail)


class TestTornTail:
    def test_torn_tail_skipped_rest_reads(self, tmp_path):
        d = str(tmp_path)
        _write_segment(
            os.path.join(d, "querylog.1.aaaa.jsonl"),
            [_rec(0), _rec(1)],
            tail='{"schema_v": 1, "fingerprint": "torn", "dur',
        )
        got = querylog.read_records(d)
        assert [r["fingerprint"] for r in got] == ["fp0", "fp1"]

    def test_torn_line_mid_union_does_not_hide_other_files(self, tmp_path):
        """The tear is per-file: a second process's segment still
        contributes every record."""
        d = str(tmp_path)
        _write_segment(
            os.path.join(d, "querylog.1.aaaa.jsonl"), [_rec(0)], tail="{garbage"
        )
        _write_segment(os.path.join(d, "querylog.2.bbbb.jsonl"), [_rec(1), _rec(2)])
        fps = {r["fingerprint"] for r in querylog.read_records(d)}
        assert fps == {"fp0", "fp1", "fp2"}

    def test_empty_and_missing_directory(self, tmp_path):
        assert querylog.read_records(str(tmp_path / "nope")) == []
        assert querylog.read_valid_records(str(tmp_path / "nope")) == []


class TestCrashedWriterPickup:
    def test_unsealed_active_file_reads_after_mid_rotate_crash(self, tmp_path):
        """A writer that crashed between the active file's fsync and
        the sealed rename leaves an UNSEALED active file; the union
        reads it next to a healthy writer's segments — zero loss."""
        d = str(tmp_path / "obslog")
        faults.set_crash("mid_querylog_rotate", "raise")
        log = querylog.QueryLog(d, max_bytes=256, max_files=8)
        written = 0
        with pytest.raises(SimulatedCrash):
            for i in range(64):
                assert log.append(_rec(i, fingerprint=f"dead{i}"))
                written += 1
        written += 1  # the rotating append was durable pre-crash
        # a healthy incarnation (fresh tag) appends alongside
        log2 = querylog.QueryLog(d, max_bytes=1 << 20, max_files=8)
        for i in range(3):
            assert log2.append(_rec(i, fingerprint=f"live{i}"))
        log2.close()
        got = querylog.read_valid_records(d)
        fps = [r["fingerprint"] for r in got]
        assert sum(1 for f in fps if f.startswith("dead")) == written
        assert sum(1 for f in fps if f.startswith("live")) == 3
        for r in got:
            assert querylog.validate_record(r) is None, r


class TestSchemaVersionSkip:
    def test_unknown_schema_v_skipped_with_counter(self, tmp_path):
        d = str(tmp_path)
        _write_segment(
            os.path.join(d, "querylog.1.aaaa.jsonl"),
            [
                _rec(0),
                _rec(1, schema_v=querylog.SCHEMA_V + 7),  # future binary
                _rec(2, schema_v="one"),  # corrupt type
                _rec(3, schema_v=True),  # bool is not an int here
                _rec(4),
            ],
        )
        before = metrics.querylog_skipped_total.value
        got = querylog.read_valid_records(d)
        assert [r["fingerprint"] for r in got] == ["fp0", "fp4"]
        assert metrics.querylog_skipped_total.value - before == 3

    def test_read_records_keeps_what_valid_reader_drops(self, tmp_path):
        """``read_records`` stays the raw union (crash tests and future
        binaries use it); only ``read_valid_records`` filters."""
        d = str(tmp_path)
        _write_segment(
            os.path.join(d, "querylog.1.aaaa.jsonl"),
            [_rec(0), _rec(1, schema_v=99)],
        )
        assert len(querylog.read_records(d)) == 2
        assert len(querylog.read_valid_records(d)) == 1
