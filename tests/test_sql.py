"""SQL surface: parse → same IR → same optimizer → index rewrites apply.

The architectural claim mirrors the reference's session extension
(HyperspaceSparkSessionExtension.scala:44-69): SQL is just another front
door into the one optimizer, so an index-served DataFrame query and its
SQL spelling produce the same plan and the same answer.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


@pytest.fixture
def views(session, tmp_path):
    rng = np.random.default_rng(4)
    d1 = tmp_path / "items"
    d1.mkdir()
    pq.write_table(
        pa.table(
            {
                "k": pa.array(rng.integers(0, 30, 400), type=pa.int64()),
                "qty": pa.array(rng.integers(1, 10, 400), type=pa.int64()),
                "tag": pa.array([["red", "blue", "green"][i % 3] for i in range(400)]),
            }
        ),
        d1 / "a.parquet",
    )
    d2 = tmp_path / "dims"
    d2.mkdir()
    pq.write_table(
        pa.table(
            {
                "dk": pa.array(np.arange(30), type=pa.int64()),
                "w": pa.array(rng.normal(size=30)),
            }
        ),
        d2 / "a.parquet",
    )
    items = session.read.parquet(str(d1))
    dims = session.read.parquet(str(d2))
    items.create_or_replace_temp_view("items")
    dims.create_or_replace_temp_view("dims")
    return items, dims


class TestSqlBasics:
    def test_select_star_where(self, session, views):
        out = session.sql("SELECT * FROM items WHERE k = 3").collect()
        items, _ = views
        want = items.filter(items["k"] == 3).collect()
        assert sorted_table(out).equals(sorted_table(want))

    def test_projection_and_operators(self, session, views):
        out = session.sql(
            "SELECT k, qty FROM items WHERE qty >= 5 AND tag <> 'red'"
        ).collect()
        assert out.column_names == ["k", "qty"]
        assert all(q >= 5 for q in out.column("qty").to_pylist())

    def test_in_and_null_and_not(self, session, views):
        out = session.sql(
            "SELECT k FROM items WHERE k IN (1, 2, 3) AND tag IS NOT NULL"
        ).collect()
        assert set(out.column("k").to_pylist()) <= {1, 2, 3}

    def test_group_by_order_limit(self, session, views):
        out = session.sql(
            "SELECT tag, SUM(qty) AS total, COUNT(*) AS n FROM items "
            "GROUP BY tag ORDER BY tag ASC LIMIT 2"
        ).collect()
        assert out.column_names == ["tag", "total", "n"]
        assert out.num_rows == 2
        assert out.column("tag").to_pylist() == ["blue", "green"]

    def test_join(self, session, views):
        items, dims = views
        out = session.sql(
            "SELECT k, qty, w FROM items JOIN dims ON k = dk WHERE qty > 7"
        ).collect()
        want = (
            items.join(dims, on=items["k"] == dims["dk"])
            .filter(items["qty"] > 7)
            .select("k", "qty", "w")
            .collect()
        )
        assert sorted_table(out).equals(sorted_table(want))

    def test_group_by_case_insensitive_spelling(self, session, views):
        out = session.sql(
            "SELECT Tag, SUM(qty) AS t FROM items GROUP BY tag"
        ).collect()
        assert out.column_names == ["tag", "t"]
        assert out.num_rows == 3

    def test_between(self, session, views):
        out = session.sql(
            "SELECT k, qty FROM items WHERE qty BETWEEN 3 AND 5"
        ).collect()
        assert out.num_rows > 0
        assert all(3 <= q <= 5 for q in out.column("qty").to_pylist())
        out2 = session.sql(
            "SELECT k FROM items WHERE qty NOT BETWEEN 3 AND 5 AND k = 1"
        ).collect()
        items, _ = views
        want = items.filter(
            ~((items["qty"] >= 3) & (items["qty"] <= 5)) & (items["k"] == 1)
        ).collect()
        assert out2.num_rows == want.num_rows

    def test_order_by_unselected_column(self, session, views):
        out = session.sql(
            "SELECT k FROM items ORDER BY qty DESC LIMIT 5"
        ).collect()
        assert out.column_names == ["k"] and out.num_rows == 5
        items, _ = views
        want = (
            items.sort(("qty", False)).limit(5).select("k").collect()
        )
        assert out.column("k").to_pylist() == want.column("k").to_pylist()

    def test_negative_literal(self, session, views):
        out = session.sql("SELECT k FROM items WHERE k > -1").collect()
        assert out.num_rows == 400

    def test_not_in_with_null_returns_no_rows(self, session, views):
        # SQL three-valued logic: x NOT IN (1, NULL) is never TRUE
        out = session.sql(
            "SELECT k FROM items WHERE k NOT IN (1, NULL)"
        ).collect()
        assert out.num_rows == 0
        # while plain IN with a NULL still matches the listed value
        out = session.sql("SELECT k FROM items WHERE k IN (1, NULL)").collect()
        assert set(out.column("k").to_pylist()) == {1}

    def test_errors(self, session, views):
        with pytest.raises(HyperspaceException, match="Unknown table"):
            session.sql("SELECT * FROM nope")
        with pytest.raises(HyperspaceException, match="GROUP BY"):
            session.sql("SELECT k, SUM(qty) FROM items")
        with pytest.raises(HyperspaceException, match="syntax"):
            session.sql("SELECT k FROM items WHERE k ~ 3")


class TestSqlUsesIndexes:
    def test_sql_filter_is_index_served(self, session, views, tmp_path):
        items, _ = views
        hs = Hyperspace(session)
        hs.create_index(items, CoveringIndexConfig("sqlidx", ["k"], ["qty"]))
        session.enable_hyperspace()
        df = session.sql("SELECT k, qty FROM items WHERE k = 7")
        plan = df.explain()
        assert "Hyperspace(Type: CI, Name: sqlidx" in plan
        got = df.collect()
        session.disable_hyperspace()
        base = session.sql("SELECT k, qty FROM items WHERE k = 7").collect()
        assert sorted_table(got).equals(sorted_table(base))
        assert got.num_rows > 0


class TestDateKeywordDisambiguation:
    """`DATE` is a keyword only when a quoted string follows; a column
    literally named `date` stays usable as a comparison operand."""

    @pytest.fixture
    def date_view(self, session, tmp_path):
        d = tmp_path / "dated"
        d.mkdir()
        pq.write_table(
            pa.table(
                {
                    "a": pa.array(["x", "y", "z", "y"]),
                    "date": pa.array(["x", "q", "z", "n"]),
                    "d": pa.array(
                        np.array(
                            ["1994-01-01", "1995-06-01", "1994-01-01", "1996-01-01"],
                            dtype="datetime64[D]",
                        )
                    ),
                }
            ),
            d / "a.parquet",
        )
        session.register_view("dated", session.read.parquet(str(d)))
        return session

    def test_column_named_date_as_operand(self, date_view):
        out = date_view.sql(
            "SELECT a FROM dated WHERE a = date"
        ).collect()
        assert sorted(out.column("a").to_pylist()) == ["x", "z"]

    def test_date_literal_still_parses(self, date_view):
        out = date_view.sql(
            "SELECT a FROM dated WHERE d = DATE '1994-01-01'"
        ).collect()
        assert sorted(out.column("a").to_pylist()) == ["x", "z"]

    def test_column_named_date_on_left(self, date_view):
        out = date_view.sql(
            "SELECT date FROM dated WHERE date = 'q'"
        ).collect()
        assert out.column("date").to_pylist() == ["q"]
