"""Fault injection through the DI factory seams.

Reference pattern: ``IndexCollectionManagerTest`` swaps mock
FileSystem/log-manager factories (``index/factories.scala:26-50``) to
exercise failure paths. Here a failing log/data manager is injected via
``hyperspace_tpu.factories`` and the action protocol's recovery contract
is asserted: a mid-action crash leaves a transient state that blocks
further operations until ``cancel()`` rolls back to the last stable state.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu import factories
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_manager import IndexLogManager


@pytest.fixture
def src(tmp_path):
    d = tmp_path / "src"
    d.mkdir()
    rng = np.random.default_rng(0)
    pq.write_table(
        pa.table(
            {
                "k": pa.array(rng.integers(0, 20, 100), type=pa.int64()),
                "v": pa.array(rng.normal(size=100)),
            }
        ),
        d / "a.parquet",
    )
    return str(d)


class FailingEndLogManager(IndexLogManager):
    """Crashes on the action's end-phase write (the second write_log)."""

    def __init__(self, path):
        super().__init__(path)
        self._writes = 0

    def write_log(self, log_id, entry):
        self._writes += 1
        if self._writes >= 2:
            raise OSError("injected: storage failed at end()")
        return super().write_log(log_id, entry)


def test_crash_at_end_leaves_transient_state_cancel_recovers(
    session, src, monkeypatch
):
    hs = Hyperspace(session)
    df = session.read.parquet(src)
    monkeypatch.setattr(factories, "log_manager_factory", FailingEndLogManager)
    with pytest.raises(OSError, match="injected"):
        hs.create_index(df, CoveringIndexConfig("fidx", ["k"], ["v"]))
    # back to real managers: index is stuck in transient CREATING
    monkeypatch.setattr(factories, "log_manager_factory", IndexLogManager)
    session.index_manager.clear_cache()
    entry = session.index_manager._managers("fidx")[0].get_latest_log()
    assert entry.state == States.CREATING
    # further operations are blocked until cancel
    with pytest.raises(HyperspaceException):
        hs.refresh_index("fidx")
    hs.cancel("fidx")
    entry = session.index_manager._managers("fidx")[0].get_latest_log()
    assert entry.state in States.STABLE_STATES
    # and a clean re-create now succeeds
    session.index_manager.clear_cache()
    hs.create_index(df, CoveringIndexConfig("fidx2", ["k"], ["v"]))
    assert (
        session.index_manager.get_index_log_entry("fidx2").state
        == States.ACTIVE
    )


class FailingDataManager:
    """Data manager whose version allocation always fails (op() crash)."""

    def __init__(self, path):
        raise OSError("injected: data manager unavailable")


def test_data_manager_failure_does_not_corrupt_log(session, src, monkeypatch):
    hs = Hyperspace(session)
    df = session.read.parquet(src)
    monkeypatch.setattr(factories, "data_manager_factory", FailingDataManager)
    with pytest.raises(OSError, match="injected"):
        hs.create_index(df, CoveringIndexConfig("didx", ["k"], ["v"]))
    monkeypatch.setattr(factories, "data_manager_factory", IndexDataManager)
    session.index_manager.clear_cache()
    # nothing was written: index does not exist, create works afterwards
    assert session.index_manager.get_index_log_entry("didx") is None
    hs.create_index(df, CoveringIndexConfig("didx", ["k"], ["v"]))
    assert (
        session.index_manager.get_index_log_entry("didx").state == States.ACTIVE
    )


class FailAfterNWritesLogManager(IndexLogManager):
    """Crashes on the Nth write_log call across all instances."""

    fail_at = 2
    _count = 0

    def write_log(self, log_id, entry):
        type(self)._count += 1
        if type(self)._count == self.fail_at:
            raise OSError("injected: crash mid-refresh")
        return super().write_log(log_id, entry)


def test_crash_during_refresh_recovers_to_previous_version(
    session, src, monkeypatch
):
    """A refresh that crashes at end() leaves REFRESHING; cancel() rolls
    back to the previous ACTIVE version and the index still serves."""
    import pyarrow.parquet as _pq

    hs = Hyperspace(session)
    df = session.read.parquet(src)
    hs.create_index(df, CoveringIndexConfig("ridx", ["k"], ["v"]))
    # appended file so refresh has work to do
    rng2 = np.random.default_rng(1)
    _pq.write_table(
        pa.table(
            {
                "k": pa.array(rng2.integers(0, 20, 30), type=pa.int64()),
                "v": pa.array(rng2.normal(size=30)),
            }
        ),
        src + "/b.parquet",
    )
    session.index_manager.clear_cache()
    FailAfterNWritesLogManager._count = 0
    monkeypatch.setattr(
        factories, "log_manager_factory", FailAfterNWritesLogManager
    )
    with pytest.raises(OSError, match="injected"):
        hs.refresh_index("ridx", C.REFRESH_MODE_FULL)
    monkeypatch.setattr(factories, "log_manager_factory", IndexLogManager)
    session.index_manager.clear_cache()
    assert (
        session.index_manager._managers("ridx")[0].get_latest_log().state
        == States.REFRESHING
    )
    hs.cancel("ridx")
    session.index_manager.clear_cache()
    entry = session.index_manager.get_index_log_entry("ridx")
    assert entry.state == States.ACTIVE
    # the rolled-back index still serves the ORIGINAL data correctly
    session.enable_hyperspace()
    df0 = session.read.parquet(src + "/a.parquet")
    q = df0.filter(df0["k"] == 3).select("k", "v")
    got = q.collect()
    session.disable_hyperspace()
    base = q.collect()
    key = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
    assert key(got).equals(key(base))
