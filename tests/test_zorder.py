"""Z-order covering index tests.

Mirrors ``zordercovering/ZOrderFieldTest.scala`` (encoding order
properties) and ``E2EHyperspaceZOrderIndexTest.scala`` (serve + results
differential; any indexed column may be constrained).
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def sorted_table(t):
    return t.sort_by([(c, "ascending") for c in t.column_names])


class TestZAddress:
    def test_order_encoding_preserves_order(self):
        from hyperspace_tpu.io.columnar import Column
        from hyperspace_tpu.ops.zorder import order_u64_np

        ints = Column.from_arrow(pa.array([-5, -1, 0, 3, 2**40], type=pa.int64()))
        e = order_u64_np(ints)
        assert (e[:-1] < e[1:]).all()
        floats = Column.from_arrow(pa.array([-1e9, -1.5, -0.0, 0.25, 3e7]))
        e = order_u64_np(floats)
        assert (e[:-1] < e[1:]).all()
        strings = Column.from_arrow(pa.array(["b", "a", "c"]))
        e = order_u64_np(strings)
        assert e[1] < e[0] < e[2]

    def test_null_sorts_first(self):
        from hyperspace_tpu.io.columnar import Column
        from hyperspace_tpu.ops.zorder import order_u64_np

        c = Column.from_arrow(pa.array([5, None, -3], type=pa.int64()))
        e = order_u64_np(c)
        assert e[1] == 0 and e[1] < e[2] < e[0]

    def test_z_permutation_locality(self):
        """Sorting by z-address groups near points of BOTH dimensions: for
        a grid, each contiguous quarter of the z-order touches at most a
        quadrant-ish bounding box, unlike a single-column sort."""
        from hyperspace_tpu.io.columnar import Column
        from hyperspace_tpu.ops.zorder import z_order_permutation

        n = 32
        xs, ys = np.meshgrid(np.arange(n), np.arange(n))
        xs, ys = xs.ravel(), ys.ravel()
        cx = Column.from_arrow(pa.array(xs, type=pa.int64()))
        cy = Column.from_arrow(pa.array(ys, type=pa.int64()))
        perm = z_order_permutation([cx, cy], bits=8)
        quarter = len(perm) // 4
        for q in range(4):
            idx = perm[q * quarter : (q + 1) * quarter]
            span_x = xs[idx].max() - xs[idx].min()
            span_y = ys[idx].max() - ys[idx].min()
            # each z-order quarter of a 32x32 grid stays within a half-ish
            # range in both dims (a column sort would span the full 0..31
            # in the secondary dim)
            assert span_x <= n // 2 + 1 and span_y <= n // 2 + 1, (
                q, span_x, span_y,
            )


class TestZOrderIndexE2E:
    def test_create_and_serve_any_indexed_col(self, session, hs, sample_parquet):
        df = session.read.parquet(sample_parquet)
        hs.create_index(
            df,
            ZOrderCoveringIndexConfig("zidx", ["clicks", "imprs"], ["query"]),
        )
        listing = hs.indexes()
        assert listing.column("name").to_pylist() == ["zidx"]
        session.enable_hyperspace()
        # predicate on the SECOND indexed column only — covering rule would
        # refuse (first-indexed-col), z-order rule must accept
        q = lambda d: d.filter(d["imprs"] >= 50).select("imprs", "query")
        plan = q(df).explain()
        assert "Hyperspace(Type: ZOCI, Name: zidx" in plan
        session.disable_hyperspace()
        base = q(df).collect()
        session.enable_hyperspace()
        got = q(df).collect()
        assert sorted_table(got).equals(sorted_table(base))
        assert got.num_rows > 0

    def test_multi_partition_write(self, session, hs, sample_parquet):
        session.conf.set(C.ZORDER_TARGET_SOURCE_BYTES_PER_PARTITION, 2000)
        df = session.read.parquet(sample_parquet)
        hs.create_index(df, ZOrderCoveringIndexConfig("zidx", ["clicks"]))
        entry = session.index_manager.get_index_log_entry("zidx")
        assert len(entry.content.files) > 1

    def test_refresh_incremental(self, session, hs, sample_parquet):
        import os

        df = session.read.parquet(sample_parquet)
        hs.create_index(
            df, ZOrderCoveringIndexConfig("zidx", ["clicks"], ["query"])
        )
        extra = pa.table(
            {
                "date": ["2019-01-01"] * 4,
                "rguid": ["a", "b", "c", "d"],
                "clicks": pa.array([11, 12, 13, 14], pa.int64()),
                "query": ["zz"] * 4,
                "imprs": pa.array([1, 2, 3, 4], pa.int64()),
            }
        )
        pq.write_table(extra, os.path.join(sample_parquet, "part-z.parquet"))
        hs.refresh_index("zidx", "incremental")
        session.enable_hyperspace()
        session.index_manager.clear_cache()
        df2 = session.read.parquet(sample_parquet)
        q = lambda d: d.filter(d["clicks"] <= 20).select("clicks", "query")
        plan = q(df2).explain()
        assert "ZOCI" in plan
        session.disable_hyperspace()
        base = q(df2).collect()
        session.enable_hyperspace()
        assert sorted_table(q(df2).collect()).equals(sorted_table(base))
