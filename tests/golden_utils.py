"""Shared golden-file machinery for the plan-stability suites.

One copy of the reference's SPARK_GENERATE_GOLDEN_FILES protocol
(``goldstandard/PlanStabilitySuite.scala:46-290``): plan simplification
(paths and log versions normalized so plans are stable across machines
and reruns), regenerate-on-flag, and the compare-with-diff assertion.
Used by ``test_plan_stability.py`` (TPC-H-mini) and
``test_tpch_plan_stability.py`` (the 22-query TPC-H corpus).
"""

import os
import re

GENERATE = os.environ.get("HS_GENERATE_GOLDEN_FILES") == "1"


def simplify_plan(plan_str: str, root: str) -> str:
    """Path- and version-independent plan text."""
    s = plan_str.replace(root, "<tpch>")
    s = re.sub(r"LogVersion: \d+", "LogVersion: N", s)
    s = re.sub(r"/[^ \[\]]*/indexes", "<system>", s)
    return s + "\n"


def check_or_generate(golden_path: str, got: str, name: str):
    """Compare against the approved plan, or (re)write it under the
    HS_GENERATE_GOLDEN_FILES=1 flow. Returns True when the file was
    regenerated (caller skips the test)."""
    if GENERATE:
        os.makedirs(os.path.dirname(golden_path), exist_ok=True)
        with open(golden_path, "w") as f:
            f.write(got)
        return True
    assert os.path.exists(golden_path), (
        f"Missing golden file {golden_path}; run with HS_GENERATE_GOLDEN_FILES=1"
    )
    with open(golden_path) as f:
        want = f.read()
    assert got == want, (
        f"Plan changed for {name}.\n--- approved ---\n{want}\n--- got ---\n{got}\n"
        "If intentional, regenerate with HS_GENERATE_GOLDEN_FILES=1 and review."
    )
    return False
