"""Operation-log manager tests (reference: index/IndexLogManagerImplTest.scala)."""

import os

from hyperspace_tpu.constants import States
from hyperspace_tpu.metadata.data_manager import IndexDataManager, version_from_path
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.metadata.path_resolver import PathResolver
from hyperspace_tpu.config import Config
from hyperspace_tpu import constants as C

from test_metadata_entry import make_entry


def test_write_and_read_log(tmp_path):
    mgr = IndexLogManager(str(tmp_path / "idx"))
    entry = make_entry(state=States.CREATING)
    assert mgr.write_log(0, entry) is True
    got = mgr.get_log(0)
    assert got is not None and got.state == States.CREATING
    assert mgr.get_latest_id() == 0


def test_write_log_occ_conflict(tmp_path):
    mgr = IndexLogManager(str(tmp_path / "idx"))
    assert mgr.write_log(0, make_entry(state=States.CREATING)) is True
    # Second writer loses the race on the same id.
    assert mgr.write_log(0, make_entry(state=States.CREATING)) is False


def test_latest_stable_pointer_and_fallback(tmp_path):
    mgr = IndexLogManager(str(tmp_path / "idx"))
    assert mgr.get_latest_stable_log() is None
    assert mgr.write_log(0, make_entry(state=States.CREATING))
    assert mgr.get_latest_stable_log() is None  # no stable state yet
    assert mgr.write_log(1, make_entry(state=States.ACTIVE))
    # Without the pointer file, backwards scan finds id 1.
    found = mgr.get_latest_stable_log()
    assert found is not None and found.state == States.ACTIVE and found.id == 1
    # Pointer file path.
    assert mgr.create_latest_stable_log(1) is True
    found2 = mgr.get_latest_stable_log()
    assert found2 is not None and found2.id == 1
    # Pointer to a transient state is rejected.
    assert mgr.write_log(2, make_entry(state=States.REFRESHING))
    assert mgr.create_latest_stable_log(2) is False


def test_get_index_versions(tmp_path):
    mgr = IndexLogManager(str(tmp_path / "idx"))
    mgr.write_log(0, make_entry(state=States.CREATING))
    mgr.write_log(1, make_entry(state=States.ACTIVE))
    mgr.write_log(2, make_entry(state=States.REFRESHING))
    mgr.write_log(3, make_entry(state=States.ACTIVE))
    assert mgr.get_index_versions([States.ACTIVE]) == [3, 1]
    assert mgr.get_index_versions([States.CREATING, States.REFRESHING]) == [2, 0]


def test_data_manager_versions(tmp_path):
    root = str(tmp_path / "idx")
    dm = IndexDataManager(root)
    assert dm.get_latest_version_id() is None
    os.makedirs(dm.get_path(0))
    os.makedirs(dm.get_path(3))
    assert dm.get_all_versions() == [0, 3]
    assert dm.get_latest_version_id() == 3
    assert dm.get_path(3).endswith("v__=3")
    dm.delete(3)
    assert dm.get_latest_version_id() == 0


def test_version_from_path():
    assert version_from_path("/idx/v__=7/part-0.parquet") == 7
    assert version_from_path("/idx/v__=12") == 12
    assert version_from_path("/idx/nope/part-0.parquet") is None


def test_path_resolver_case_insensitive(tmp_path):
    conf = Config({C.INDEX_SYSTEM_PATH: str(tmp_path)})
    r = PathResolver(conf)
    os.makedirs(str(tmp_path / "MyIndex"))
    assert r.get_index_path("myindex") == str(tmp_path / "MyIndex")
    assert r.get_index_path("other") == str(tmp_path / "other")
    assert r.all_index_paths() == [str(tmp_path / "MyIndex")]


def test_write_log_does_not_stamp_id_on_conflict(tmp_path):
    mgr = IndexLogManager(str(tmp_path / "idx"))
    winner = make_entry(state=States.CREATING)
    assert mgr.write_log(0, winner) and winner.id == 0
    loser = make_entry(state=States.CREATING)
    loser.id = 99
    assert mgr.write_log(0, loser) is False
    assert loser.id == 99  # untouched on conflict


def test_concurrent_writers_exactly_one_wins(tmp_path):
    """OCC under REAL concurrency: N threads race to write the same log id;
    exactly one atomic create-if-absent succeeds and the surviving content
    is exactly the winner's (reference: concurrent writeLog failure paths,
    IndexLogManagerImplTest)."""
    import threading

    path = str(tmp_path / "idx")
    results = {}
    barrier = threading.Barrier(8)

    def writer(i):
        mgr = IndexLogManager(path)
        entry = make_entry(state=States.CREATING)
        entry.name = f"writer-{i}"
        barrier.wait()
        results[i] = mgr.write_log(1, entry)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    winners = [i for i, ok in results.items() if ok]
    assert len(winners) == 1, results
    stored = IndexLogManager(path).get_log(1)
    assert stored.name == f"writer-{winners[0]}"


# ---------------------------------------------------------------------------
# Torn-entry handling (PR 10 satellite): typed LogCorruptedError, reads
# route around corruption, publish is dirent-durable
# ---------------------------------------------------------------------------


def test_torn_entry_raises_typed_error(tmp_path):
    from hyperspace_tpu.exceptions import LogCorruptedError

    mgr = IndexLogManager(str(tmp_path / "idx"))
    assert mgr.write_log(0, make_entry(state=States.ACTIVE))
    with open(mgr._path_for(1), "w") as f:
        f.write('{"state": "REFRESH')  # truncated mid-write
    try:
        mgr.get_log(1)
        assert False, "expected LogCorruptedError"
    except LogCorruptedError as exc:
        assert "1" in exc.path and exc.reason


def test_stable_scan_and_versions_skip_torn_entries(tmp_path):
    mgr = IndexLogManager(str(tmp_path / "idx"))
    assert mgr.write_log(0, make_entry(state=States.CREATING))
    assert mgr.write_log(1, make_entry(state=States.ACTIVE))
    with open(mgr._path_for(2), "w") as f:
        f.write("not json at all")
    found = mgr.get_latest_stable_log()
    assert found is not None and found.id == 1
    assert mgr.get_index_versions([States.ACTIVE]) == [1]


def test_torn_pointer_falls_back_to_scan(tmp_path):
    mgr = IndexLogManager(str(tmp_path / "idx"))
    assert mgr.write_log(0, make_entry(state=States.ACTIVE))
    assert mgr.create_latest_stable_log(0)
    with open(mgr._latest_stable_path, "w") as f:
        f.write('{"truncat')
    assert mgr.get_latest_stable_pointer_id() is None
    found = mgr.get_latest_stable_log()
    assert found is not None and found.id == 0


def test_overwrite_log_replaces_in_place(tmp_path):
    mgr = IndexLogManager(str(tmp_path / "idx"))
    entry = make_entry(state=States.REFRESHING)
    assert mgr.write_log(3, entry)
    entry.properties["recovery.leaseExpiresAtMs"] = "12345"
    mgr.overwrite_log(3, entry)
    got = mgr.get_log(3)
    assert got.properties["recovery.leaseExpiresAtMs"] == "12345"
    assert got.id == 3
