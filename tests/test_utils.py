import os
import threading

from hyperspace_tpu.utils import files, paths
from hyperspace_tpu.utils.resolver import ResolvedColumn, resolve


def test_atomic_write_if_absent(tmp_path):
    p = str(tmp_path / "log" / "1")
    assert files.atomic_write_if_absent(p, "first") is True
    assert files.atomic_write_if_absent(p, "second") is False
    assert files.read_text(p) == "first"


def test_atomic_write_concurrent(tmp_path):
    """Exactly one of N concurrent writers must win (OCC contract)."""
    p = str(tmp_path / "log" / "7")
    results = []

    def writer(i):
        results.append(files.atomic_write_if_absent(p, f"writer-{i}"))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(results) == 1
    assert files.read_text(p).startswith("writer-")


def test_list_leaf_files(tmp_path):
    (tmp_path / "a" / "b").mkdir(parents=True)
    (tmp_path / "a" / "x.txt").write_text("xx")
    (tmp_path / "a" / "b" / "y.txt").write_text("yyy")
    listed = files.list_leaf_files(str(tmp_path))
    names = sorted(os.path.basename(p) for p, _, _ in listed)
    assert names == ["x.txt", "y.txt"]
    sizes = {os.path.basename(p): s for p, s, _ in listed}
    assert sizes == {"x.txt": 2, "y.txt": 3}


def test_data_path_filter():
    assert paths.is_data_path("/x/part-0.parquet")
    assert not paths.is_data_path("/x/_hyperspace_log")
    assert not paths.is_data_path("/x/.hidden")
    assert not paths.is_data_path("/x/_SUCCESS")


def test_resolve_case_insensitive():
    assert resolve(["Query", "CLICKS"], ["query", "clicks", "imprs"]) == [
        ResolvedColumn("query"),
        ResolvedColumn("clicks"),
    ]
    assert resolve(["nope"], ["query"]) is None
    assert resolve(["Query"], ["query"], case_sensitive=True) is None


def test_resolve_nested():
    r = resolve(["a.b"], ["x"], nested_available=["a.b"])
    assert r == [ResolvedColumn("a.b", True)]
    assert r[0].normalized_name == "__hs_nested.a.b"
    back = ResolvedColumn.from_normalized("__hs_nested.a.b")
    assert back.is_nested and back.name == "a.b"


class TestReadTableSchemaEvolution:
    def test_multi_file_type_widening(self, tmp_path):
        """Batched multi-file read must fall back to permissive concat when
        schemas differ (Delta/Iceberg type widening)."""
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        from hyperspace_tpu.io.parquet import read_table

        p1 = tmp_path / "a.parquet"
        p2 = tmp_path / "b.parquet"
        pq.write_table(pa.table({"y": pa.array([1, 2], type=pa.int32())}), p1)
        big = 1 << 40
        pq.write_table(pa.table({"y": pa.array([big], type=pa.int64())}), p2)
        t = read_table([str(p1), str(p2)])
        assert t.column("y").type == pa.int64()
        assert t.column("y").to_pylist() == [1, 2, big]

    def test_multi_file_same_schema_order(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from hyperspace_tpu.io.parquet import read_table

        paths = []
        for i in range(6):
            p = tmp_path / f"f{i}.parquet"
            pq.write_table(pa.table({"x": pa.array([i] * 3)}), p)
            paths.append(str(p))
        t = read_table(paths)
        assert t.column("x").to_pylist() == [v for i in range(6) for v in [i] * 3]
