import os
import threading

from hyperspace_tpu.utils import files, paths
from hyperspace_tpu.utils.resolver import ResolvedColumn, resolve


def test_atomic_write_if_absent(tmp_path):
    p = str(tmp_path / "log" / "1")
    assert files.atomic_write_if_absent(p, "first") is True
    assert files.atomic_write_if_absent(p, "second") is False
    assert files.read_text(p) == "first"


def test_atomic_write_concurrent(tmp_path):
    """Exactly one of N concurrent writers must win (OCC contract)."""
    p = str(tmp_path / "log" / "7")
    results = []

    def writer(i):
        results.append(files.atomic_write_if_absent(p, f"writer-{i}"))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(results) == 1
    assert files.read_text(p).startswith("writer-")


def test_list_leaf_files(tmp_path):
    (tmp_path / "a" / "b").mkdir(parents=True)
    (tmp_path / "a" / "x.txt").write_text("xx")
    (tmp_path / "a" / "b" / "y.txt").write_text("yyy")
    listed = files.list_leaf_files(str(tmp_path))
    names = sorted(os.path.basename(p) for p, _, _ in listed)
    assert names == ["x.txt", "y.txt"]
    sizes = {os.path.basename(p): s for p, s, _ in listed}
    assert sizes == {"x.txt": 2, "y.txt": 3}


def test_data_path_filter():
    assert paths.is_data_path("/x/part-0.parquet")
    assert not paths.is_data_path("/x/_hyperspace_log")
    assert not paths.is_data_path("/x/.hidden")
    assert not paths.is_data_path("/x/_SUCCESS")


def test_resolve_case_insensitive():
    assert resolve(["Query", "CLICKS"], ["query", "clicks", "imprs"]) == [
        ResolvedColumn("query"),
        ResolvedColumn("clicks"),
    ]
    assert resolve(["nope"], ["query"]) is None
    assert resolve(["Query"], ["query"], case_sensitive=True) is None


def test_resolve_nested():
    r = resolve(["a.b"], ["x"], nested_available=["a.b"])
    assert r == [ResolvedColumn("a.b", True)]
    assert r[0].normalized_name == "__hs_nested.a.b"
    back = ResolvedColumn.from_normalized("__hs_nested.a.b")
    assert back.is_nested and back.name == "a.b"
