"""Serve-server mode: the in-RAM index data cache.

The data-plane extension of the reference's metadata TTL cache
(``CachingIndexCollectionManager.scala:38-108``). Tests follow the
project's differential doctrine: every cached serve must return exactly
what the uncached serve returns, across filter shapes, joins, hybrid
scans and refresh-driven invalidation.
"""

import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.execution.serve_cache import (
    ScanCacheEntry,
    ServeCache,
    batch_nbytes,
    estimate_nbytes,
    file_fingerprint,
)
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.io.columnar import Column, ColumnarBatch


def sorted_table(t: pa.Table) -> pa.Table:
    return t.sort_by([(c, "ascending") for c in t.column_names])


class TestServeCacheUnit:
    def test_lru_eviction_by_bytes(self):
        c = ServeCache(max_bytes=100)
        c.put("a", 1, 40)
        c.put("b", 2, 40)
        assert c.get("a") == 1  # touch a: b becomes LRU
        c.put("c", 3, 40)  # evicts b
        assert c.get("b") is None
        assert c.get("a") == 1
        assert c.get("c") == 3
        assert c.resident_bytes == 80

    def test_oversized_value_not_cached(self):
        c = ServeCache(max_bytes=10)
        c.put("big", 1, 11)
        assert c.get("big") is None
        assert len(c) == 0

    def test_replace_updates_bytes(self):
        c = ServeCache(max_bytes=100)
        c.put("a", 1, 60)
        c.put("a", 2, 30)
        assert c.resident_bytes == 30
        assert c.get("a") == 2

    def test_hit_miss_counters(self):
        c = ServeCache(max_bytes=100)
        c.get("x")
        c.put("x", 1, 1)
        c.get("x")
        assert (c.hits, c.misses) == (1, 1)

    def test_clear(self):
        c = ServeCache(max_bytes=100)
        c.put("a", 1, 10)
        c.clear()
        assert c.get("a") is None
        assert c.resident_bytes == 0


class TestEstimateNbytes:
    """estimate_nbytes — the one sizing ruler shared by the cache
    governor (batch_nbytes, ScanCacheEntry.budget_nbytes) and the
    residency witness (testing/residency_witness.py, hslint HS1004).
    The doctrine under test: a value is charged for every byte it PINS,
    not just the extent of the slice it exposes."""

    def test_numpy_view_charges_owner(self):
        a = np.arange(1000, dtype=np.int64)
        assert estimate_nbytes(a) == 8000
        # a 10-element view keeps all 8000 bytes alive
        assert estimate_nbytes(a[:10]) == 8000
        # a view of a view still finds the owner
        assert estimate_nbytes(a[:100][5:10]) == 8000

    def test_owning_copy_charges_its_own_extent(self):
        a = np.arange(1000, dtype=np.int64)
        assert estimate_nbytes(a[:10].copy()) == 80

    def test_arrow_backed_column_charges_buffer(self):
        # zero-copy decode: the Column's numpy values array is a view
        # over the arrow buffer — the pre-fix accounting charged only
        # the slice extent and undercounted exactly these entries
        t = pa.table({"k": pa.array(range(100_000), type=pa.int64())})
        col = ColumnarBatch.from_arrow(t).column("k")
        assert estimate_nbytes(col) >= 100_000 * 8
        assert batch_nbytes(ColumnarBatch.from_arrow(t)) >= 100_000 * 8

    def test_string_column_charges_dictionary(self):
        t = pa.table({"s": pa.array(["aa", "bb", "aa", "cc"])})
        col = ColumnarBatch.from_arrow(t).column("s")
        # int32 codes + the three dictionary strings with per-object
        # overhead (an empty str is ~49 resident bytes)
        assert estimate_nbytes(col) >= 4 * 4 + 3 * (2 + 49)

    def test_pyarrow_table_uses_buffer_size(self):
        t = pa.table({"k": pa.array(range(100), type=pa.int64())})
        assert estimate_nbytes(t) == t.get_total_buffer_size()

    def test_entry_budget_charges_pinned_bytes(self):
        # a ScanCacheEntry holding a view over a large decoded array is
        # charged what it pins (the whole owner), not the subset extent
        # — the governor can no longer undercount
        n = 10_000
        big = np.arange(n, dtype=np.int64)
        sub = Column("numeric", pa.int64(), values=big[:5])
        entry = ScanCacheEntry([(0, 5)]).with_new_columns({"k": sub})
        assert entry.budget_nbytes >= n * 8

    def test_cache_accounting_matches_estimate(self):
        c = ServeCache(max_bytes=1 << 30)
        t = pa.table({"k": pa.array(range(1000), type=pa.int64())})
        batch = ColumnarBatch.from_arrow(t)
        a = np.arange(1000, dtype=np.float64)
        c.put("b", batch, estimate_nbytes(batch))
        c.put("a", a[:10], estimate_nbytes(a[:10]))
        assert c.resident_bytes == estimate_nbytes(batch) + a.nbytes


class TestFingerprint:
    def test_changes_with_content(self, tmp_path):
        p = tmp_path / "f.parquet"
        pq.write_table(pa.table({"a": [1, 2]}), str(p))
        fp1 = file_fingerprint([str(p)])
        os.utime(str(p), ns=(1, 1))  # mtime change → new fingerprint
        fp2 = file_fingerprint([str(p)])
        assert fp1 != fp2

    def test_missing_file_returns_none(self, tmp_path):
        assert file_fingerprint([str(tmp_path / "nope")]) is None


class TestScanCacheEntry:
    def _entry(self, values, segments):
        batch = ColumnarBatch.from_arrow(
            pa.table({"k": pa.array(values, type=pa.int64())})
        )
        return ScanCacheEntry(segments).with_new_columns(
            {"k": batch.column("k")}
        )

    def test_sorted_segments_detected(self):
        st = self._entry([1, 5, 9, 2, 3], [(0, 3), (3, 5)])
        rep, ok = st.column_state("k")
        assert ok
        assert rep.tolist() == [1, 5, 9, 2, 3]

    def test_unsorted_segment_detected(self):
        st = self._entry([1, 5, 3], [(0, 3)])
        _, ok = st.column_state("k")
        assert not ok

    def test_memoized(self):
        st = self._entry([1, 2], [(0, 2)])
        assert st.column_state("k") is st.column_state("k")

    def test_columns_accrue_copy_on_write(self):
        st = self._entry([1, 2], [(0, 2)])
        assert st.batch_for(["k", "v"]) is None  # v not cached yet
        b1 = st.budget_nbytes
        v = ColumnarBatch.from_arrow(
            pa.table({"v": pa.array([1.0, 2.0])})
        ).column("v")
        st2 = st.with_new_columns({"v": v})
        assert st2.batch_for(["k", "v"]).num_rows == 2
        assert st2.budget_nbytes > b1  # the copy is re-charged
        assert st.batch_for(["k", "v"]) is None  # original untouched
        # shared Column objects, not copies
        assert st2.columns["k"] is st.columns["k"]

    def test_budget_charges_rep_memo(self):
        st = self._entry([1, 2], [(0, 2)])
        # budget = column bytes + 8 bytes/row pre-charge for the key-rep
        assert st.budget_nbytes == 2 * 8 + 2 * 8


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def _lineitem(tmp_path, n=4000, n_files=4, with_floats=True):
    rng = np.random.default_rng(11)
    d = tmp_path / "tbl"
    d.mkdir()
    t = pa.table(
        {
            "k": rng.integers(0, 500, n).astype(np.int64),
            "d": pa.array(
                (
                    np.datetime64("1994-01-01")
                    + rng.integers(0, 900, n).astype("timedelta64[D]")
                ).astype("datetime64[D]")
            ),
            "q": rng.integers(1, 51, n).astype(np.int64),
            "p": rng.normal(100.0, 30.0, n),
            "s": pa.array([f"s{v % 7}" for v in range(n)]),
        }
    )
    per = n // n_files
    for i in range(n_files):
        pq.write_table(
            t.slice(i * per, per if i < n_files - 1 else n - i * per),
            str(d / f"part{i}.parquet"),
        )
    return str(d)


class TestCachedFilterDifferential:
    """Cached serve == uncached serve for every filter shape, and the
    cache actually hits."""

    QUERIES = [
        lambda df: df.filter(df["k"] == 123).select("k", "q"),
        lambda df: df.filter(df["k"] == -1).select("k"),  # empty result
        lambda df: df.filter(df["k"] < 30).select("k", "q", "p"),
        lambda df: df.filter(df["k"] >= 480).select("k", "d"),
        lambda df: df.filter(df["k"].isin(3, 490, 77)).select("k", "q"),
        lambda df: df.filter((df["k"] == 123) & (df["q"] > 25)).select("k", "q"),
        # float predicate column: narrowing must refuse range-by-rep
        lambda df: df.filter((df["k"] == 123) & (df["p"] < 100.0)).select("k", "p"),
        # string equality
        lambda df: df.filter((df["k"] == 123) & (df["s"] == "s3")).select("k", "s"),
    ]

    def test_filter_shapes(self, session, hs, tmp_path):
        src = _lineitem(tmp_path)
        df = session.read.parquet(src)
        hs.create_index(
            df, CoveringIndexConfig("ix", ["k"], ["d", "q", "p", "s"])
        )
        session.conf.set(C.INDEX_FILTER_RULE_USE_BUCKET_SPEC, True)
        session.enable_hyperspace()
        expected = [sorted_table(q(df).collect()) for q in self.QUERIES]
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        # twice: first populates, second must hit
        for _ in range(2):
            for q, exp in zip(self.QUERIES, expected):
                got = sorted_table(q(df).collect())
                assert got.equals(exp)
        assert session.serve_cache.hits > 0
        session.conf.set(C.SERVE_CACHE_ENABLED, False)

    def test_refresh_invalidates_by_fingerprint(self, session, hs, tmp_path):
        src = _lineitem(tmp_path)
        df = session.read.parquet(src)
        session.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        hs.create_index(df, CoveringIndexConfig("ix", ["k"], ["q"]))
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        session.enable_hyperspace()
        q = lambda d: d.filter(d["k"] == 123).select("k", "q")
        before = q(df).collect().num_rows
        assert before == q(df).collect().num_rows  # cache populated
        # append source rows with k=123 and refresh incrementally: the new
        # index version has new files → new fingerprints → no stale serve
        extra = pa.table(
            {
                "k": pa.array([123] * 5, type=pa.int64()),
                "d": pa.array(np.full(5, np.datetime64("1998-01-01"), dtype="datetime64[D]")),
                "q": pa.array([7] * 5, type=pa.int64()),
                "p": pa.array([1.0] * 5),
                "s": pa.array(["sX"] * 5),
            }
        )
        pq.write_table(extra, os.path.join(src, "extra.parquet"))
        hs.refresh_index("ix", C.REFRESH_MODE_INCREMENTAL)
        session.index_manager.clear_cache()
        df2 = session.read.parquet(src)
        got = q(df2).collect()
        assert got.num_rows == before + 5
        session.conf.set(C.SERVE_CACHE_ENABLED, False)


class TestCachedJoinDifferential:
    def _join(self, session, df_o, df_i):
        j = df_o.join(df_i, on=df_o["ok"] == df_i["k"])
        return j.select("ok", "v", "q")

    def _mk(self, session, hs, tmp_path):
        src = _lineitem(tmp_path)
        o = tmp_path / "orders"
        o.mkdir()
        rng = np.random.default_rng(5)
        for i in range(2):
            pq.write_table(
                pa.table(
                    {
                        "ok": np.arange(i * 250, (i + 1) * 250, dtype=np.int64),
                        "v": rng.normal(0, 1, 250),
                    }
                ),
                str(o / f"p{i}.parquet"),
            )
        df_i = session.read.parquet(src)
        df_o = session.read.parquet(str(o))
        hs.create_index(df_i, CoveringIndexConfig("ix_i", ["k"], ["q"]))
        hs.create_index(df_o, CoveringIndexConfig("ix_o", ["ok"], ["v"]))
        return df_o, df_i, src

    def test_join_cached_equals_uncached(self, session, hs, tmp_path):
        df_o, df_i, _src = self._mk(session, hs, tmp_path)
        session.enable_hyperspace()
        plan = self._join(session, df_o, df_i).explain()
        assert plan.count("Hyperspace(Type: CI") == 2
        expected = sorted_table(self._join(session, df_o, df_i).collect())
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        for _ in range(2):
            got = sorted_table(self._join(session, df_o, df_i).collect())
            assert got.equals(expected)
        assert session.serve_cache.hits > 0
        session.conf.set(C.SERVE_CACHE_ENABLED, False)

    def test_hybrid_joinside_cached_and_invalidated(self, session, hs, tmp_path):
        """Repeated hybrid joins on a STABLE appended state hit the
        joinside cache (keyed on index + appended file fingerprints);
        a further append changes the fingerprint and serves fresh."""
        df_o, df_i, src = self._mk(session, hs, tmp_path)
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        session.enable_hyperspace()

        def append(name, ks):
            pq.write_table(
                pa.table(
                    {
                        "k": pa.array(ks, type=pa.int64()),
                        "d": pa.array(
                            np.full(
                                len(ks),
                                np.datetime64("1998-01-01"),
                                dtype="datetime64[D]",
                            )
                        ),
                        "q": pa.array([7] * len(ks), type=pa.int64()),
                        "p": pa.array([1.0] * len(ks)),
                        "s": pa.array(["sH"] * len(ks)),
                    }
                ),
                os.path.join(src, name),
            )
            session.index_manager.clear_cache()
            return session.read.parquet(src)

        df_i2 = append("hybrid-a.parquet", [3, 490])
        plan = self._join(session, df_o, df_i2).explain()
        assert plan.count("Hyperspace(Type: CI") == 2, plan
        first = sorted_table(self._join(session, df_o, df_i2).collect())
        hits0 = session.serve_cache.hits
        again = sorted_table(self._join(session, df_o, df_i2).collect())
        assert again.equals(first)
        assert session.serve_cache.hits > hits0  # joinside served from RAM
        # the UNION-shaped side must itself be cached: exactly the new
        # behavior under test, pinned by its two-fingerprint key (a plain
        # index-scan side's key has one fingerprint and hit before too)
        union_keys = [
            k
            for k in session.serve_cache._entries
            if k[0] == "joinside" and len(k[1]) == 2
        ]
        assert union_keys, "hybrid union joinside entry missing"
        # differential against the unindexed engine on the same state
        session.disable_hyperspace()
        raw = sorted_table(self._join(session, df_o, df_i2).collect())
        assert first.equals(raw)
        session.enable_hyperspace()
        # a FURTHER append must not serve the stale cached union
        df_i3 = append("hybrid-b.parquet", [3])
        more = sorted_table(self._join(session, df_o, df_i3).collect())
        assert more.num_rows == first.num_rows + 1
        session.disable_hyperspace()
        raw3 = sorted_table(self._join(session, df_o, df_i3).collect())
        assert more.equals(raw3)
        session.enable_hyperspace()
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, False)
        session.conf.set(C.SERVE_CACHE_ENABLED, False)

    def test_hybrid_scan_after_cache_populated(self, session, hs, tmp_path):
        df_o, df_i, src = self._mk(session, hs, tmp_path)
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        session.enable_hyperspace()
        first = sorted_table(self._join(session, df_o, df_i).collect())
        assert sorted_table(self._join(session, df_o, df_i).collect()).equals(
            first
        )
        # append ~ a few source rows AFTER the cache is warm
        extra = pa.table(
            {
                "k": pa.array([3, 3, 490], type=pa.int64()),
                "d": pa.array(np.full(3, np.datetime64("1998-01-01"), dtype="datetime64[D]")),
                "q": pa.array([9, 9, 9], type=pa.int64()),
                "p": pa.array([1.0] * 3),
                "s": pa.array(["sX"] * 3),
            }
        )
        pq.write_table(extra, os.path.join(src, "appended.parquet"))
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, True)
        session.index_manager.clear_cache()
        df_i2 = session.read.parquet(src)
        hybrid = sorted_table(self._join(session, df_o, df_i2).collect())
        session.disable_hyperspace()
        raw = sorted_table(self._join(session, df_o, df_i2).collect())
        assert hybrid.equals(raw)
        assert hybrid.num_rows == first.num_rows + 3
        session.conf.set(C.INDEX_HYBRID_SCAN_ENABLED, False)
        session.conf.set(C.SERVE_CACHE_ENABLED, False)


class TestPreparedJoinSide:
    def _bs(self, data):
        return {
            b: ColumnarBatch.from_arrow(pa.table(t)) for b, t in data.items()
        }

    def test_subset_and_mismatched_buckets(self):
        from hyperspace_tpu.execution.join_exec import co_bucketed_join

        lbs = self._bs(
            {
                0: {"k": pa.array([1, 2], type=pa.int64())},
                1: {"k": pa.array([5], type=pa.int64())},
            }
        )
        rbs = self._bs(
            {
                1: {"rk": pa.array([5, 5], type=pa.int64())},
                2: {"rk": pa.array([9], type=pa.int64())},
            }
        )
        out = co_bucketed_join(lbs, rbs, [("k", "rk")])
        assert out.num_rows == 2
        assert out.column("k").values.tolist() == [5, 5]

    def test_null_keys_never_match(self):
        from hyperspace_tpu.execution.join_exec import co_bucketed_join

        lbs = self._bs({0: {"k": pa.array([1, None, 3], type=pa.int64())}})
        rbs = self._bs({0: {"rk": pa.array([None, 3], type=pa.int64())}})
        out = co_bucketed_join(lbs, rbs, [("k", "rk")])
        assert out.column("k").values.tolist() == [3]

    def test_multi_key_verified(self):
        from hyperspace_tpu.execution.join_exec import co_bucketed_join

        lbs = self._bs(
            {
                0: {
                    "a": pa.array([1, 1, 2], type=pa.int64()),
                    "b": pa.array([10, 11, 10], type=pa.int64()),
                }
            }
        )
        rbs = self._bs(
            {
                0: {
                    "ra": pa.array([1, 2], type=pa.int64()),
                    "rb": pa.array([11, 10], type=pa.int64()),
                }
            }
        )
        out = co_bucketed_join(lbs, rbs, [("a", "ra"), ("b", "rb")])
        got = sorted(
            zip(
                out.column("a").values.tolist(),
                out.column("b").values.tolist(),
            )
        )
        assert got == [(1, 11), (2, 10)]

    def test_empty_side(self):
        from hyperspace_tpu.execution.join_exec import co_bucketed_join

        lbs = self._bs({0: {"k": pa.array([1], type=pa.int64())}})
        assert co_bucketed_join(lbs, {}, [("k", "rk")]) is None

    def test_trailing_empty_bucket(self):
        # regression: offs[-1] == n (empty last bucket, e.g. a selective
        # filter emptied it) must not index past the sortedness array
        from hyperspace_tpu.execution.join_exec import prepare_join_side

        empty = ColumnarBatch.from_arrow(
            pa.table({"k": pa.array([], type=pa.int64())})
        )
        lbs = self._bs({0: {"k": pa.array([1, 2, 3], type=pa.int64())}})
        lbs[1] = empty
        prep = prepare_join_side(lbs, ["k"])
        assert prep.sorted_buckets
        assert prep.sizes.tolist() == [3, 0]

    def test_empty_middle_bucket_join(self):
        from hyperspace_tpu.execution.join_exec import co_bucketed_join

        empty = ColumnarBatch.from_arrow(
            pa.table({"k": pa.array([], type=pa.int64())})
        )
        lbs = self._bs(
            {
                0: {"k": pa.array([7, 8], type=pa.int64())},
                2: {"k": pa.array([9], type=pa.int64())},
            }
        )
        lbs[1] = empty
        rbs = self._bs(
            {
                0: {"rk": pa.array([8], type=pa.int64())},
                1: {"rk": pa.array([], type=pa.int64())},
                2: {"rk": pa.array([9, 9], type=pa.int64())},
            }
        )
        out = co_bucketed_join(lbs, rbs, [("k", "rk")])
        assert sorted(out.column("k").values.tolist()) == [8, 9, 9]


class TestCachedFilteredAggregate:
    def test_aggregate_over_cached_filter_scan(self, session, hs, tmp_path):
        """An aggregate above an index-served FILTER runs off the cached
        scan entry (a filterless aggregate is never index-rewritten —
        the rules require a predicate or join, as in the reference)."""
        from hyperspace_tpu import functions as F

        src = _lineitem(tmp_path)
        df = session.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("agix", ["k"], ["q", "p"]))
        session.enable_hyperspace()
        q = lambda: (
            df.filter(df["k"] < 200)
            .group_by("k")
            .agg(F.sum("q").alias("sq"), F.count().alias("n"))
        )
        plan = q().explain()
        assert "Hyperspace(Type: CI" in plan
        expected = sorted_table(q().collect())
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        first = sorted_table(q().collect())  # populates
        second = sorted_table(q().collect())  # hits
        assert first.equals(expected) and second.equals(expected)
        assert session.serve_cache.hits > 0
        session.conf.set(C.SERVE_CACHE_ENABLED, False)

    def test_filter_queries_share_column_entries(self, session, hs, tmp_path):
        """The per-file-set entry accrues columns: two filter queries
        over overlapping projections decode each column once (one
        ('scan', fp) key total)."""
        src = _lineitem(tmp_path)
        df = session.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("shix", ["k"], ["q", "p"]))
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        session.enable_hyperspace()
        df.filter(df["k"] > 100).select("k", "q").collect()
        df.filter(df["k"] > 300).select("k", "p").collect()
        assert len(session.serve_cache) == 1
        session.conf.set(C.SERVE_CACHE_ENABLED, False)


class TestServeCacheConcurrency:
    def test_racing_first_touch_queries_agree(self, session, hs, tmp_path):
        """Concurrent FIRST-TOUCH queries (cache empty when the threads
        start) must all return the correct answer and leave the cache
        consistent (the OCC-stress doctrine applied to the serve cache)."""
        import threading

        src = _lineitem(tmp_path)
        df = session.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("rcix", ["k"], ["q"]))
        session.enable_hyperspace()
        expected = sorted_table(  # computed BEFORE the cache exists
            df.filter(df["k"] == 123).select("k", "q").collect()
        )
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        results, errors = [], []

        def worker():
            try:
                got = sorted_table(
                    df.filter(df["k"] == 123).select("k", "q").collect()
                )
                results.append(got)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert len(results) == 8
        for got in results:
            assert got.equals(expected)
        # later queries hit the (single) cached entry
        session.serve_cache.hits = 0
        sorted_table(df.filter(df["k"] == 123).select("k", "q").collect())
        assert session.serve_cache.hits > 0
        session.conf.set(C.SERVE_CACHE_ENABLED, False)

    def test_racing_different_projections_copy_on_write(
        self, session, hs, tmp_path
    ):
        """Racing queries with DIFFERENT column sets force concurrent
        column additions to the same ('scan', fp) entry — the
        copy-on-write publication must never expose a torn entry (the
        in-place mutation bug showed as 'dictionary changed size during
        iteration' in budget accounting)."""
        import threading

        src = _lineitem(tmp_path)
        df = session.read.parquet(src)
        hs.create_index(
            df, CoveringIndexConfig("cwix", ["k"], ["q", "p", "s", "d"])
        )
        session.enable_hyperspace()
        queries = [
            lambda: df.filter(df["k"] == 123).select("k", "q").collect(),
            lambda: df.filter(df["k"] == 200).select("k", "p").collect(),
            lambda: df.filter(df["k"] == 300).select("k", "s").collect(),
            lambda: df.filter(df["k"] == 400).select("k", "d").collect(),
        ]
        expected = [sorted_table(q()) for q in queries]
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        results = {i: [] for i in range(len(queries))}
        errors = []

        def worker(i):
            try:
                for _ in range(4):
                    results[i].append(sorted_table(queries[i]()))
            except Exception as e:
                errors.append(e)

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(len(queries))
            for _ in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        for i, exp in enumerate(expected):
            for got in results[i]:
                assert got.equals(exp), i
        session.conf.set(C.SERVE_CACHE_ENABLED, False)


class TestCachedZOrderServe:
    def test_zorder_filter_cached_differential(self, session, hs, tmp_path):
        """Z-order index scans cache too; their files are z-address
        sorted (NOT single-column sorted), so the sorted-segment narrow
        must detect unsorted columns and fall back to the full mask —
        still answering from RAM."""
        from hyperspace_tpu.indexes.zorder import ZOrderCoveringIndexConfig

        src = _lineitem(tmp_path)
        df = session.read.parquet(src)
        hs.create_index(
            df, ZOrderCoveringIndexConfig("zc", ["k", "q"], ["p"])
        )
        session.enable_hyperspace()
        q = lambda: df.filter(
            (df["k"] >= 100) & (df["k"] < 150) & (df["q"] > 10)
        ).select("k", "q", "p")
        plan = q().explain()
        assert "Hyperspace(Type: ZOCI" in plan
        expected = sorted_table(q().collect())
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        first = sorted_table(q().collect())
        second = sorted_table(q().collect())
        assert first.equals(expected) and second.equals(expected)
        assert session.serve_cache.hits > 0
        session.conf.set(C.SERVE_CACHE_ENABLED, False)


class TestPublicationMerge:
    def test_peek_does_not_count(self):
        c = ServeCache(max_bytes=100)
        c.put("a", 1, 10)
        assert c.peek("a") == 1 and c.peek("b") is None
        assert (c.hits, c.misses) == (0, 0)

    def test_evict_recreate_race_keeps_needed_columns(self, session, hs, tmp_path):
        """If the entry is evicted and re-created with a DIFFERENT
        projection between a thread's get and its publication, the
        published union must still cover the thread's columns (the
        stale-extra merge) — previously batch_for returned None and the
        query crashed."""
        import pyarrow as pa

        from hyperspace_tpu.execution.serve_cache import ScanCacheEntry
        from hyperspace_tpu.execution import executor as X

        src = _lineitem(tmp_path)
        df = session.read.parquet(src)
        hs.create_index(df, CoveringIndexConfig("evix", ["k"], ["q", "p"]))
        session.conf.set(C.SERVE_CACHE_ENABLED, True)
        session.enable_hyperspace()
        # populate {k, q}
        expected = sorted_table(
            df.filter(df["k"] == 123).select("k", "q").collect()
        )
        cache = session.serve_cache
        (key,) = [k for k in cache._entries if k[0] == "scan"]
        # simulate the race: replace the entry with a DIFFERENT projection
        # ({p} only) between this query's get and publication, by patching
        # peek to swap the entry the first time it's consulted
        real_peek = cache.peek
        swapped = {"done": False}

        def racing_peek(k):
            if not swapped["done"] and k == key:
                swapped["done"] = True
                entry = real_peek(k)
                other = ScanCacheEntry(entry.segments).with_new_columns(
                    {"p": entry.columns.get("p")}
                    if "p" in entry.columns
                    else {}
                )
                cache.put(k, other, 1)
                return other
            return real_peek(k)

        cache.peek = racing_peek
        try:
            # query needing {k, d}: 'd' is missing -> publication path runs
            got = sorted_table(
                df.filter(df["k"] == 123).select("k", "d").collect()
            )
            assert got.num_rows == expected.num_rows
            # and the original projection still answers correctly
            again = sorted_table(
                df.filter(df["k"] == 123).select("k", "q").collect()
            )
            assert again.equals(expected)
        finally:
            cache.peek = real_peek
        session.conf.set(C.SERVE_CACHE_ENABLED, False)


class TestMemoryGovernor:
    """ServeCache as the serve plane's memory governor (ISSUE 8): exact
    byte accounting, budget never exceeded — even observed racily — and
    resident-set telemetry."""

    def test_high_water_and_eviction_telemetry(self):
        c = ServeCache(max_bytes=100)
        c.put(("scan", "a"), 1, 60)
        c.put(("joinside", "b"), 2, 40)
        assert c.high_water_bytes == 100
        c.put(("scan", "c"), 3, 30)  # evicts ("scan","a")
        assert c.get(("scan", "a")) is None
        st = c.stats()
        assert st["evictions"] == 1 and st["evicted_bytes"] == 60
        assert st["high_water_bytes"] == 100
        assert st["resident_bytes"] == 70
        assert c.bytes_by_kind() == {"joinside": 40, "scan": 30}

    def test_put_never_overshoots_budget(self):
        # eviction happens BEFORE insert: the ledger can never pass the
        # budget even mid-critical-section (unsynchronized telemetry
        # probes rely on this)
        c = ServeCache(max_bytes=100)
        c.put(("scan", 1), "x", 90)
        c.put(("scan", 2), "y", 90)
        assert c.resident_bytes == 90
        assert c.high_water_bytes <= 100

    def test_insert_failures_counted_under_fault(self):
        from hyperspace_tpu.testing import faults

        faults.reset()
        try:
            c = ServeCache(max_bytes=100)
            faults.set_fault("cache_insert", "transient:1")
            c.put(("scan", 1), "x", 10)  # dropped
            assert c.get(("scan", 1)) is None
            assert c.insert_failures == 1
            c.put(("scan", 1), "x", 10)  # recovered
            assert c.get(("scan", 1)) == "x"
        finally:
            faults.reset()

    def test_evict_kind_racing_get_put(self):
        """Two writer threads + a reader hammer the cache while the main
        thread repeatedly evict_kind()s; accounting must stay exact, the
        budget must hold at every unsynchronized probe, and no operation
        may error (the lock-discipline audit's regression test)."""
        import threading

        c = ServeCache(max_bytes=5_000)
        stop = threading.Event()
        errors = []

        def writer(tag):
            try:
                i = 0
                while not stop.is_set():
                    kind = ("scan", "joinside", "delta", "aggstate")[i % 4]
                    c.put((kind, tag, i % 11), ("v", tag, i), 100 + (i % 7))
                    c.get((kind, tag, (i + 5) % 11))
                    c.peek((kind, tag, (i + 2) % 11))
                    i += 1
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def prober():
            try:
                while not stop.is_set():
                    # unsynchronized reads must never observe an
                    # over-budget ledger or a torn stats snapshot
                    assert c.resident_bytes <= c.max_bytes
                    st = c.stats()
                    assert st["resident_bytes"] <= st["max_bytes"]
                    c.bytes_by_kind()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(2)
        ] + [threading.Thread(target=prober)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 1.0
        evicted = 0
        while time.monotonic() < deadline:
            evicted += c.evict_kind("scan")
            c.evict_kind("delta")
            c.evict_kind("aggstate")
        stop.set()
        for t in threads:
            t.join(30)
        assert not errors, errors
        assert evicted > 0  # the race was real
        # exact accounting after the storm
        with c._lock:
            assert c._bytes == sum(nb for _v, nb in c._entries.values())
        assert c.resident_bytes <= c.max_bytes
        assert c.high_water_bytes <= c.max_bytes
        c.evict_kind("scan")  # drain whatever landed after the storm
        assert c.evict_kind("scan") == 0  # and a second pass finds nothing


class TestSpillTier:
    """The spill-aware governor (docs/out-of-core.md): cold data-plane
    entries demote to fsync'd files under ``_hyperspace_spill/`` instead
    of evicting to oblivion, restore bit-identically as zero-copy mmap
    views, and the tier itself is byte-capped LRU."""

    def _batch(self, seed=5, n=4_000):
        rng = np.random.default_rng(seed)
        return ColumnarBatch.from_arrow(
            pa.table(
                {
                    "k": rng.integers(0, 100, n).astype(np.int64),
                    "v": rng.normal(0, 1, n),
                    "tag": pa.array(rng.choice(["x", "y", "z"], n)),
                }
            )
        )

    def _spilled_cache(self, tmp_path, batch):
        """A cache sized so inserting a second entry demotes the first."""
        nb = batch_nbytes(batch)
        c = ServeCache(
            max_bytes=nb + 16,
            spill_dir=str(tmp_path / "_hyperspace_spill"),
            spill_max_bytes=1 << 30,
        )
        c.put(("scan", "fp-a", ("k",)), batch, nb)
        # zonemap is not a spill kind: promoting fp-a back on restore
        # displaces this entry to oblivion, not to a second spill file
        c.put(("zonemap", "fp-b"), "displacer", nb)
        return c

    def test_demote_restore_bit_identical(self, tmp_path):
        batch = self._batch()
        c = self._spilled_cache(tmp_path, batch)
        assert c.spill_demotes == 1
        paths = c.spill_paths()
        assert len(paths) == 1 and all(os.path.exists(p) for p in paths)
        restored = c.get(("scan", "fp-a", ("k",)))
        assert restored is not None
        assert restored.to_arrow().equals(batch.to_arrow())
        assert c.spill_restores == 1
        # restore unlinks the file; the live mapping keeps its pages
        assert not any(os.path.exists(p) for p in paths)
        # the mmap-aware ruler charges views, not decoded heap bytes
        assert estimate_nbytes(restored) < batch_nbytes(batch) / 4
        st = c.stats()
        assert st["spill_demotes"] == 1 and st["spill_restores"] == 1
        assert st["spill_bytes"] > 0

    def test_torn_spill_file_degrades_to_miss(self, tmp_path):
        batch = self._batch()
        c = self._spilled_cache(tmp_path, batch)
        (path,) = c.spill_paths()
        with open(path, "wb") as f:
            f.write(b"HSSP1\0garbage")  # torn: magic ok, body junk
        assert c.get(("scan", "fp-a", ("k",))) is None
        assert c.spill_drops == 1
        assert not os.path.exists(path)  # wreckage reaped

    def test_spill_tier_byte_cap_reaps_oldest(self, tmp_path):
        batch = self._batch()
        nb = batch_nbytes(batch)
        blob_est = len(
            __import__(
                "hyperspace_tpu.execution.serve_cache",
                fromlist=["_spill_encode"],
            )._spill_encode(batch)
        )
        c = ServeCache(
            max_bytes=nb + 16,
            spill_dir=str(tmp_path / "_hyperspace_spill"),
            spill_max_bytes=int(blob_est * 1.5),  # room for ONE blob
        )
        for i in range(3):
            c.put(("scan", f"fp-{i}", ("k",)), self._batch(seed=i), nb)
        assert c.spill_demotes == 2
        assert len(c.spill_paths()) == 1  # cap held: oldest reaped
        assert c.stats()["spill_resident_bytes"] <= int(blob_est * 1.5)

    def test_unspillable_value_dropped_not_crashed(self, tmp_path):
        nb = 1_000
        c = ServeCache(
            max_bytes=nb + 16,
            spill_dir=str(tmp_path / "_hyperspace_spill"),
            spill_max_bytes=1 << 30,
        )
        c.put(("scan", "fp-a"), lambda: None, nb)  # refuses to pickle
        c.put(("scan", "fp-b"), "displacer", nb)
        assert c.spill_drops == 1
        assert c.get(("scan", "fp-a")) is None
        assert c.spill_paths() == set()

    def test_metadata_kinds_evict_to_oblivion(self, tmp_path):
        nb = 1_000
        c = ServeCache(
            max_bytes=nb + 16,
            spill_dir=str(tmp_path / "_hyperspace_spill"),
            spill_max_bytes=1 << 30,
        )
        c.put(("zonemap", "fp-a"), {"z": 1}, nb)
        c.put(("scan", "fp-b"), "displacer", nb)
        assert c.spill_demotes == 0  # zonemap is not a spill kind
        assert c.get(("zonemap", "fp-a")) is None

    def test_clear_empties_spill_tier(self, tmp_path):
        batch = self._batch()
        c = self._spilled_cache(tmp_path, batch)
        paths = c.spill_paths()
        assert paths
        c.clear()
        assert c.spill_paths() == set()
        assert not any(os.path.exists(p) for p in paths)


class TestMmapEstimate:
    """Satellite of the zero-copy read path: estimate_nbytes charges
    views over a registered memory-mapped region as O(1) tokens, so the
    governor never double-counts the kernel page cache as heap."""

    def test_open_mmap_table_charges_tokens(self, tmp_path):
        import pyarrow.ipc as ipc

        from hyperspace_tpu.io.columnar import open_mmap_table

        n = 200_000
        t = pa.table({"k": pa.array(range(n), type=pa.int64())})
        path = str(tmp_path / "t.arrow")
        with ipc.new_file(path, t.schema) as w:
            w.write_table(t)
        heap_copy = pa.table({"k": pa.array(range(n), type=pa.int64())})
        assert estimate_nbytes(heap_copy) >= n * 8
        mapped = open_mmap_table(path)
        assert mapped.equals(heap_copy)  # same bytes, different backing
        assert estimate_nbytes(mapped) < n  # tokens, not 1.6 MB of heap
        # a batch decoded zero-copy over the mapping stays token-priced
        batch = ColumnarBatch.from_arrow(mapped)
        assert estimate_nbytes(batch) < n

    def test_mapped_region_retires_with_owner(self, tmp_path):
        import gc

        import pyarrow.ipc as ipc

        from hyperspace_tpu.execution import serve_cache as sc
        from hyperspace_tpu.io.columnar import open_mmap_table

        t = pa.table({"k": pa.array(range(50_000), type=pa.int64())})
        path = str(tmp_path / "t.arrow")
        with ipc.new_file(path, t.schema) as w:
            w.write_table(t)
        # other tests' mappings may still be registered until their
        # finalizers run — track THIS mapping's address, not the count
        gc.collect()
        before = set(sc._mmap_regions)
        mapped = open_mmap_table(path)
        new = set(sc._mmap_regions) - before
        assert len(new) == 1
        del mapped
        gc.collect()
        assert not (new & set(sc._mmap_regions))  # finalizer retired it
