"""Nested (struct) column indexing end-to-end.

Reference: ``util/ResolverUtils.scala:130-234`` (nested fields flattened
to ``__hs_nested.``-prefixed columns), ``actions/CreateAction.scala:69-71``
(opt-in gate). Here the flattening happens at relation construction
(io/columnar.flatten_schema_fields): struct leaves are first-class flat
columns everywhere, virtual over source files (struct-root extraction at
read, io/parquet._resolve_nested_columns) and literal inside index data.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import constants as C
from hyperspace_tpu.constants import NESTED_FIELD_PREFIX
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.indexes.covering import CoveringIndexConfig
from hyperspace_tpu.io.columnar import flatten_schema_fields


def sorted_table(t: pa.Table) -> pa.Table:
    return t.sort_by([(c, "ascending") for c in t.column_names])


class TestFlattenSchema:
    def test_struct_flattened_depth_first(self):
        t = pa.struct(
            [
                ("leaf", pa.struct([("cnt", pa.int64())])),
                ("id", pa.string()),
            ]
        )
        out = flatten_schema_fields((("k", pa.int64()), ("nested", t)))
        assert out == (
            ("k", pa.int64()),
            (NESTED_FIELD_PREFIX + "nested.leaf.cnt", pa.int64()),
            (NESTED_FIELD_PREFIX + "nested.id", pa.string()),
        )

    def test_list_leaves_dropped(self):
        t = pa.struct([("xs", pa.list_(pa.int64())), ("v", pa.float64())])
        out = flatten_schema_fields((("s", t),))
        assert out == ((NESTED_FIELD_PREFIX + "s.v", pa.float64()),)

    def test_plain_fields_untouched(self):
        fields = (("a", pa.int64()), ("b", pa.string()))
        assert flatten_schema_fields(fields) == fields

    def test_fixed_size_list_leaf_dropped(self):
        t = pa.struct(
            [("fs", pa.list_(pa.int64(), 2)), ("v", pa.int64())]
        )
        out = flatten_schema_fields((("s", t),))
        assert out == ((NESTED_FIELD_PREFIX + "s.v", pa.int64()),)

    def test_dotted_field_names_dropped(self):
        # a field name containing '.' cannot round-trip through the dotted
        # flattened name — it must be skipped, not mis-split at read time
        t = pa.struct([("a.b", pa.int64()), ("v", pa.int64())])
        out = flatten_schema_fields((("s", t),))
        assert out == ((NESTED_FIELD_PREFIX + "s.v", pa.int64()),)
        # dotted struct ROOT name: left as-is (no flattening)
        out2 = flatten_schema_fields((("x.y", t),))
        assert out2 == (("x.y", t),)


@pytest.fixture
def hs(session):
    return Hyperspace(session)


def _nested_dataset(tmp_path, n=600, n_files=3):
    """Rows with a struct column: nested = {leaf: {cnt}, id}, plus nulls."""
    d = tmp_path / "nested_tbl"
    d.mkdir()
    rng = np.random.default_rng(21)
    per = n // n_files
    for i in range(n_files):
        cnt = rng.integers(0, 40, per)
        rows = []
        for j in range(per):
            if (i * per + j) % 97 == 0:
                rows.append(None)  # null struct row
            else:
                rows.append(
                    {"leaf": {"cnt": int(cnt[j])}, "id": f"id{(i * per + j) % 9}"}
                )
        t = pa.table(
            {
                "k": pa.array(
                    rng.integers(0, 50, per).astype(np.int64)
                ),
                "v": pa.array(rng.normal(0, 1, per)),
                "nested": pa.array(
                    rows,
                    type=pa.struct(
                        [
                            ("leaf", pa.struct([("cnt", pa.int64())])),
                            ("id", pa.string()),
                        ]
                    ),
                ),
            }
        )
        pq.write_table(t, str(d / f"p{i}.parquet"))
    return str(d)


class TestNestedScan:
    def test_scan_surfaces_flattened_columns(self, session, tmp_path):
        df = session.read.parquet(_nested_dataset(tmp_path))
        assert NESTED_FIELD_PREFIX + "nested.leaf.cnt" in df.columns
        assert NESTED_FIELD_PREFIX + "nested.id" in df.columns
        assert "nested" not in df.columns

    def test_dotted_access_resolves(self, session, tmp_path):
        df = session.read.parquet(_nested_dataset(tmp_path))
        col = df["nested.leaf.cnt"]
        assert col.name == NESTED_FIELD_PREFIX + "nested.leaf.cnt"

    def test_unindexed_select_and_filter(self, session, tmp_path):
        src = _nested_dataset(tmp_path)
        df = session.read.parquet(src)
        out = df.filter(df["nested.leaf.cnt"] == 7).select(
            "k", "nested.leaf.cnt"
        ).collect()
        # oracle: pyarrow-level recomputation
        import pyarrow.compute as pc

        raw = pq.read_table(sorted(
            os.path.join(src, f) for f in os.listdir(src)
        ))
        cnt = pc.struct_field(raw.column("nested"), ["leaf", "cnt"])
        expected = pc.sum(
            pc.fill_null(pc.equal(cnt, 7), False).cast(pa.int64())
        ).as_py()
        assert out.num_rows == expected > 0
        assert set(out.column_names) == {
            "k",
            NESTED_FIELD_PREFIX + "nested.leaf.cnt",
        }

    def test_group_by_sort_agg_resolve_dotted(self, session, tmp_path):
        from hyperspace_tpu import functions as F

        df = session.read.parquet(_nested_dataset(tmp_path))
        out = (
            df.group_by("nested.id")
            .agg(F.count(), F.max("nested.leaf.cnt").alias("m"))
            .collect()
        )
        assert out.num_rows > 0
        srt = df.select("k", "nested.leaf.cnt").sort("nested.leaf.cnt").collect()
        col = srt.column(NESTED_FIELD_PREFIX + "nested.leaf.cnt").to_pylist()
        non_null = [v for v in col if v is not None]
        assert non_null == sorted(non_null)

    def test_null_struct_rows_are_null_leaves(self, session, tmp_path):
        df = session.read.parquet(_nested_dataset(tmp_path))
        t = df.select("nested.id").collect()
        assert t.column(0).null_count > 0


class TestNestedIndexing:
    def test_create_gate_requires_conf(self, session, hs, tmp_path):
        df = session.read.parquet(_nested_dataset(tmp_path))
        with pytest.raises(HyperspaceException, match="supportNestedFields"):
            hs.create_index(
                df, CoveringIndexConfig("nix", ["nested.leaf.cnt"], ["v"])
            )

    def test_filter_served_and_differential(self, session, hs, tmp_path):
        src = _nested_dataset(tmp_path)
        df = session.read.parquet(src)
        session.conf.set(C.INDEX_SUPPORT_NESTED_FIELDS, True)
        hs.create_index(
            df,
            CoveringIndexConfig(
                "nix", ["nested.leaf.cnt"], ["k", "nested.id"]
            ),
        )
        entry = session.index_manager.get_index_log_entry("nix")
        assert entry.derived_dataset.indexed_columns == [
            NESTED_FIELD_PREFIX + "nested.leaf.cnt"
        ]

        def q(d):
            return d.filter(d["nested.leaf.cnt"] == 7).select(
                "k", "nested.id"
            )

        session.enable_hyperspace()
        plan = q(df).explain()
        assert "Hyperspace(Type: CI, Name: nix" in plan
        with_index = sorted_table(q(df).collect())
        session.disable_hyperspace()
        without = sorted_table(q(df).collect())
        assert with_index.equals(without)
        assert with_index.num_rows > 0

    def test_join_on_nested_key_differential(self, session, hs, tmp_path):
        src = _nested_dataset(tmp_path)
        df = session.read.parquet(src)
        dim = tmp_path / "dim"
        dim.mkdir()
        pq.write_table(
            pa.table(
                {
                    "cnt_key": np.arange(40, dtype=np.int64),
                    "label": pa.array([f"L{v}" for v in range(40)]),
                }
            ),
            str(dim / "d.parquet"),
        )
        dfd = session.read.parquet(str(dim))
        session.conf.set(C.INDEX_SUPPORT_NESTED_FIELDS, True)
        hs.create_index(
            df, CoveringIndexConfig("nj", ["nested.leaf.cnt"], ["k"])
        )
        hs.create_index(dfd, CoveringIndexConfig("dj", ["cnt_key"], ["label"]))

        def q():
            j = dfd.join(df, on=dfd["cnt_key"] == df["nested.leaf.cnt"])
            return j.select("cnt_key", "label", "k")

        session.enable_hyperspace()
        plan = q().explain()
        assert plan.count("Hyperspace(Type: CI") == 2
        with_index = sorted_table(q().collect())
        session.disable_hyperspace()
        without = sorted_table(q().collect())
        assert with_index.equals(without)
        assert with_index.num_rows > 0

    def test_incremental_refresh_with_nested(self, session, hs, tmp_path):
        src = _nested_dataset(tmp_path)
        df = session.read.parquet(src)
        session.conf.set(C.INDEX_SUPPORT_NESTED_FIELDS, True)
        session.conf.set(C.INDEX_LINEAGE_ENABLED, True)
        hs.create_index(
            df, CoveringIndexConfig("nr", ["nested.leaf.cnt"], ["k"])
        )
        session.enable_hyperspace()

        def q(d):
            return d.filter(d["nested.leaf.cnt"] == 7).select("k")

        before = q(df).collect().num_rows
        extra = pa.table(
            {
                "k": pa.array([999, 998], type=pa.int64()),
                "v": pa.array([0.0, 0.0]),
                "nested": pa.array(
                    [
                        {"leaf": {"cnt": 7}, "id": "new"},
                        {"leaf": {"cnt": 8}, "id": "new"},
                    ],
                    type=pa.struct(
                        [
                            ("leaf", pa.struct([("cnt", pa.int64())])),
                            ("id", pa.string()),
                        ]
                    ),
                ),
            }
        )
        pq.write_table(extra, os.path.join(src, "extra.parquet"))
        hs.refresh_index("nr", C.REFRESH_MODE_INCREMENTAL)
        session.index_manager.clear_cache()
        df2 = session.read.parquet(src)
        plan = q(df2).explain()
        assert "Hyperspace(Type: CI, Name: nr" in plan
        with_index = q(df2).collect()
        session.disable_hyperspace()
        assert q(df2).collect().num_rows == with_index.num_rows == before + 1
